"""Task template tests (reference: client/consul_template.go:52-534 —
render-block before start, change-mode signal/restart, KV-driven
re-render)."""
import os
import threading
import time

import pytest

import conftest

from nomad_tpu import mock
from nomad_tpu.client.template import (
    MissingDependency,
    TaskTemplateManager,
    parse_signal,
)
from nomad_tpu.consul import ServiceCatalog
from nomad_tpu.consul.catalog import CatalogEntry
from nomad_tpu.structs import structs as s

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestRendering:
    def mgr(self, tmpl, tmp_path, catalog=None, env=None, **kw):
        return TaskTemplateManager([tmpl], str(tmp_path),
                                   env or {}, catalog=catalog, **kw)

    def test_env_and_kv_functions(self, tmp_path):
        cat = ServiceCatalog()
        cat.kv_set("app/db_host", "db1.internal")
        tmpl = s.Template(
            embedded_tmpl='host={{key "app/db_host"}} user={{env "USER_X"}}',
            dest_path="local/app.conf")
        m = self.mgr(tmpl, tmp_path, catalog=cat, env={"USER_X": "svc"})
        assert m.render_all_blocking(should_abort=lambda: False)
        out = (tmp_path / "local" / "app.conf").read_text()
        assert out == "host=db1.internal user=svc"

    def test_service_function_and_range(self, tmp_path):
        cat = ServiceCatalog()
        cat.register(CatalogEntry(id="a", name="db", address="10.0.0.1",
                                  port=5432))
        cat.register(CatalogEntry(id="b", name="db", address="10.0.0.2",
                                  port=5433))
        tmpl = s.Template(
            embedded_tmpl='upstreams={{service "db"}}\n'
                          '{{range service "db"}}server {{.Address}}:{{.Port}};\n{{end}}',
            dest_path="local/lb.conf")
        m = self.mgr(tmpl, tmp_path, catalog=cat)
        assert m.render_all_blocking(should_abort=lambda: False)
        out = (tmp_path / "local" / "lb.conf").read_text()
        assert "upstreams=10.0.0.1:5432,10.0.0.2:5433" in out
        assert "server 10.0.0.1:5432;" in out and "server 10.0.0.2:5433;" in out

    def test_blocks_until_key_exists(self, tmp_path):
        cat = ServiceCatalog()
        tmpl = s.Template(embedded_tmpl='v={{key "late/key"}}',
                          dest_path="local/x")
        m = self.mgr(tmpl, tmp_path, catalog=cat)
        done = threading.Event()
        result = {}

        def run():
            result["ok"] = m.render_all_blocking(should_abort=lambda: False,
                                                 poll=0.02)
            done.set()

        threading.Thread(target=run, daemon=True).start()
        time.sleep(0.3)
        assert not done.is_set(), "render completed before the key existed"
        cat.kv_set("late/key", "arrived")
        assert done.wait(5.0) and result["ok"]
        assert (tmp_path / "local" / "x").read_text() == "v=arrived"

    def test_source_file_template(self, tmp_path):
        src = tmp_path / "tmpl.in"
        src.write_text('greeting={{env "GREET"}}')
        tmpl = s.Template(source_path=str(src), dest_path="local/out",
                          perms="0600")
        m = self.mgr(tmpl, tmp_path, env={"GREET": "hello"})
        assert m.render_all_blocking(should_abort=lambda: False)
        dest = tmp_path / "local" / "out"
        assert dest.read_text() == "greeting=hello"
        assert oct(dest.stat().st_mode & 0o777) == "0o600"

    def test_parse_signal(self):
        import signal as sigmod
        assert parse_signal("SIGHUP") == sigmod.SIGHUP
        assert parse_signal("usr1") == sigmod.SIGUSR1
        assert parse_signal("") == sigmod.SIGHUP


class TestChangeModes:
    def test_kv_change_triggers_restart_and_signal(self, tmp_path):
        cat = ServiceCatalog()
        cat.kv_set("cfg/a", "1")
        cat.kv_set("cfg/b", "1")
        restarts = []
        signals = []
        templates = [
            s.Template(embedded_tmpl='a={{key "cfg/a"}}',
                       dest_path="local/a", splay=0.0,
                       change_mode=s.TEMPLATE_CHANGE_MODE_RESTART),
            s.Template(embedded_tmpl='b={{key "cfg/b"}}',
                       dest_path="local/b", splay=0.0,
                       change_mode=s.TEMPLATE_CHANGE_MODE_SIGNAL,
                       change_signal="SIGHUP"),
        ]
        m = TaskTemplateManager(
            templates, str(tmp_path), {}, catalog=cat,
            on_signal=signals.append, on_restart=lambda: restarts.append(1))
        assert m.render_all_blocking(should_abort=lambda: False)
        m.start_watching()
        try:
            cat.kv_set("cfg/b", "2")
            assert wait_until(lambda: signals, 5.0), "signal never fired"
            assert not restarts
            assert (tmp_path / "local" / "b").read_text() == "b=2"

            cat.kv_set("cfg/a", "2")
            assert wait_until(lambda: restarts, 5.0), "restart never fired"
            assert (tmp_path / "local" / "a").read_text() == "a=2"
        finally:
            m.stop()

    def test_noop_mode_rewrites_without_action(self, tmp_path):
        cat = ServiceCatalog()
        cat.kv_set("n/x", "1")
        fired = []
        tmpl = s.Template(embedded_tmpl='x={{key "n/x"}}',
                          dest_path="local/n", splay=0.0,
                          change_mode=s.TEMPLATE_CHANGE_MODE_NOOP)
        m = TaskTemplateManager([tmpl], str(tmp_path), {}, catalog=cat,
                                on_signal=fired.append,
                                on_restart=lambda: fired.append("r"))
        assert m.render_all_blocking(should_abort=lambda: False)
        m.start_watching()
        try:
            cat.kv_set("n/x", "2")
            assert wait_until(
                lambda: (tmp_path / "local" / "n").read_text() == "x=2", 5.0)
            assert not fired
        finally:
            m.stop()


class TestEndToEnd:
    """A mock task gated on its template; KV update restarts it
    (consul_template.go render-block + change-mode restart)."""

    @pytest.fixture()
    def agent(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig

        cfg = conftest.dev_test_config()
        cfg.client.state_dir = str(tmp_path / "state")
        cfg.client.alloc_dir = str(tmp_path / "allocs")
        a = Agent(cfg)
        a.start()
        yield a
        a.shutdown()

    def test_template_gates_start_and_restarts_on_change(self, agent):
        srv, client = agent.server, agent.client
        assert wait_until(lambda: srv.node_get(client.node.id) is not None
                          and srv.node_get(client.node.id).status == "ready")
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.restart_policy = s.RestartPolicy(attempts=3, interval=300.0,
                                            delay=0.1)
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "60s"}
            t.resources.networks = []
            t.services = []
            t.templates = [s.Template(
                embedded_tmpl='setting={{key "app/config"}}',
                dest_path="local/app.conf", splay=0.0,
                change_mode=s.TEMPLATE_CHANGE_MODE_RESTART)]
        srv.job_register(job)

        # The task must NOT start while the key is missing.
        time.sleep(1.0)
        allocs = srv.job_allocations(job.id)
        assert allocs and allocs[0].client_status == \
            s.ALLOC_CLIENT_STATUS_PENDING

        agent.catalog.kv_set("app/config", "v1")
        assert wait_until(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)), 20.0), \
            "task did not start after template rendered"
        alloc = srv.job_allocations(job.id)[0]
        runner = client.get_alloc_runner(alloc.id)
        conf = os.path.join(runner.alloc_dir.task_dirs["web"].dir,
                            "local", "app.conf")
        assert open(conf).read() == "setting=v1"

        # KV change → re-render → restart (task stays/returns to running).
        agent.catalog.kv_set("app/config", "v2")
        assert wait_until(lambda: os.path.exists(conf)
                          and open(conf).read() == "setting=v2", 10.0)

        def restarted():
            a = srv.job_allocations(job.id)[0]
            st = (a.task_states or {}).get("web")
            if st is None:
                return False
            return any(e.type == s.TASK_RESTART_SIGNAL for e in st.events) \
                or sum(1 for e in st.events if e.type == s.TASK_STARTED) >= 2

        assert wait_until(restarted, 20.0), "change_mode=restart never fired"
