"""Log/file follow streaming tests (reference:
command/agent/fs_endpoint.go streaming framing + follow,
client/driver/executor/logging/rotator.go)."""
import os
import threading
import time

import pytest

import conftest

from nomad_tpu import mock
from nomad_tpu.client.fs_stream import stream_file_frames, stream_log_frames
from nomad_tpu.structs import structs as s

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def collect_frames(gen, n, timeout=10.0):
    """Pull up to n frames from a generator in a worker thread."""
    frames = []
    done = threading.Event()

    def run():
        try:
            for frame in gen:
                frames.append(frame)
                if len(frames) >= n:
                    break
        finally:
            done.set()
            close = getattr(gen, "close", None)
            if close:
                close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    done.wait(timeout)
    return frames


class TestStreamGenerators:
    def test_plain_read_then_stop(self, tmp_path):
        p = tmp_path / "file.txt"
        p.write_bytes(b"hello world")
        frames = list(stream_file_frames(str(p), "file.txt", follow=False))
        assert b"".join(f.get("Data", b"") for f in frames) == b"hello world"

    def test_origin_end_offset(self, tmp_path):
        p = tmp_path / "file.txt"
        p.write_bytes(b"0123456789")
        frames = list(stream_file_frames(str(p), "file.txt", offset=4,
                                         origin="end", follow=False))
        assert b"".join(f.get("Data", b"") for f in frames) == b"6789"

    def test_follow_sees_appends(self, tmp_path):
        p = tmp_path / "grow.log"
        p.write_bytes(b"first|")
        gen = stream_file_frames(str(p), "grow.log", follow=True, poll=0.02)
        got = []
        lock = threading.Lock()

        def run():
            for frame in gen:
                with lock:
                    got.append(frame.get("Data", b""))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with lock:
                if b"".join(got) == b"first|":
                    break
            time.sleep(0.02)
        with open(p, "ab") as fh:
            fh.write(b"second")
        deadline = time.time() + 5
        while time.time() < deadline:
            with lock:
                if b"".join(got) == b"first|second":
                    break
            time.sleep(0.02)
        with lock:
            assert b"".join(got) == b"first|second"

    def test_log_stream_follows_rotation(self, tmp_path):
        log_dir = str(tmp_path)
        f0 = tmp_path / "web.stdout.0"
        f0.write_bytes(b"AAA")
        gen = stream_log_frames(log_dir, "web", "stdout", follow=True,
                                poll=0.02)
        frames = []
        lock = threading.Lock()

        def run():
            for frame in gen:
                with lock:
                    frames.append(frame)

        t = threading.Thread(target=run, daemon=True)
        t.start()

        def data_so_far():
            with lock:
                return b"".join(f.get("Data", b"") for f in frames)

        deadline = time.time() + 5
        while time.time() < deadline and data_so_far() != b"AAA":
            time.sleep(0.02)
        # rotate: new index appears, stream must hop to it
        (tmp_path / "web.stdout.1").write_bytes(b"BBB")
        deadline = time.time() + 5
        while time.time() < deadline and data_so_far() != b"AAABBB":
            time.sleep(0.02)
        assert data_so_far() == b"AAABBB"
        with lock:
            events = [f for f in frames if f.get("FileEvent")]
        assert events and events[0]["File"].endswith("web.stdout.1")

    def test_non_follow_drains_all_rotations(self, tmp_path):
        (tmp_path / "web.stdout.0").write_bytes(b"one|")
        (tmp_path / "web.stdout.1").write_bytes(b"two|")
        (tmp_path / "web.stdout.2").write_bytes(b"three")
        frames = list(stream_log_frames(str(tmp_path), "web", "stdout",
                                        follow=False))
        assert b"".join(f.get("Data", b"") for f in frames) == b"one|two|three"

    def test_stops_when_dead_and_drained(self, tmp_path):
        (tmp_path / "web.stdout.0").write_bytes(b"done")
        alive = {"v": True}
        gen = stream_log_frames(str(tmp_path), "web", "stdout", follow=True,
                                alive=lambda: alive["v"], poll=0.01)
        frames = collect_frames(gen, 1)
        assert frames and frames[0]["Data"] == b"done"
        alive["v"] = False
        done = threading.Event()
        rest = []

        def run():
            for f in gen:
                rest.append(f)
            done.set()

        threading.Thread(target=run, daemon=True).start()
        assert done.wait(5.0), "stream did not terminate after task death"


class TestHTTPStreaming:
    """End-to-end: a running mock task tailed over the HTTP API
    (VERDICT r1 next-round #6 'a test tails a running mock task and sees
    appended frames')."""

    @pytest.fixture()
    def agent(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig

        cfg = conftest.dev_test_config()
        cfg.client.state_dir = str(tmp_path / "state")
        cfg.client.alloc_dir = str(tmp_path / "allocs")
        a = Agent(cfg)
        a.start()
        yield a
        a.shutdown()

    def _wait(self, pred, timeout=20.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return False

    def test_tail_running_task_over_http(self, agent):
        from nomad_tpu.api.client import NomadAPI

        srv = agent.server
        client = agent.client
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "60s"}
            t.resources.networks = []
            t.services = []
        srv.job_register(job)
        assert self._wait(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)))
        alloc = next(a for a in srv.job_allocations(job.id)
                     if a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING)

        # The task's rotated stdout file (executor LogRotator naming).
        runner = client.get_alloc_runner(alloc.id)
        log_dir = os.path.join(runner.alloc_dir.alloc_dir, "alloc", "logs")
        os.makedirs(log_dir, exist_ok=True)
        log0 = os.path.join(log_dir, "web.stdout.0")
        with open(log0, "ab") as fh:
            fh.write(b"line one\n")

        api = NomadAPI(address=agent.http.address)
        frames = []
        lock = threading.Lock()
        gen = api.agent.stream_logs(alloc.id, "web", "stdout", follow=True)

        def run():
            try:
                for frame in gen:
                    with lock:
                        frames.append(frame)
            except Exception:
                pass

        t = threading.Thread(target=run, daemon=True)
        t.start()

        def text():
            with lock:
                return b"".join(f.get("Data", b"") for f in frames)

        assert self._wait(lambda: b"line one\n" in text(), 10.0), \
            "initial log content never streamed"
        with open(log0, "ab") as fh:
            fh.write(b"line two\n")
        assert self._wait(lambda: b"line two\n" in text(), 10.0), \
            "appended frame never arrived over HTTP follow"

    def test_cli_logs_follow_sees_appends(self, agent):
        import io

        from nomad_tpu.cli import commands as cli

        srv = agent.server
        client = agent.client
        job = mock.job()
        job.id = job.name = "cli-follow"
        tg = job.task_groups[0]
        tg.count = 1
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "60s"}
            t.resources.networks = []
            t.services = []
        srv.job_register(job)
        assert self._wait(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)))
        alloc = next(iter(srv.job_allocations(job.id)))
        runner = client.get_alloc_runner(alloc.id)
        log_dir = os.path.join(runner.alloc_dir.alloc_dir, "alloc", "logs")
        os.makedirs(log_dir, exist_ok=True)
        log0 = os.path.join(log_dir, "web.stdout.0")
        with open(log0, "ab") as fh:
            fh.write(b"before follow\n")

        out = io.StringIO()

        def run_cli():
            cli.main(["logs", "-address", agent.http.address, "-f",
                      alloc.id, "web"], out=out)

        t = threading.Thread(target=run_cli, daemon=True)
        t.start()
        # -f tails from the end: only content appended AFTER the tail
        # starts shows up (command/logs.go origin=end).
        time.sleep(1.0)
        with open(log0, "ab") as fh:
            fh.write(b"hello from task\n")
        assert self._wait(
            lambda: "hello from task" in out.getvalue(), 10.0)
        assert "before follow" not in out.getvalue()
