"""Vault subsystem tests (reference: nomad/vault.go:234-1218 server client,
client/vaultclient renewal heap, node_endpoint.go DeriveVaultToken).
Uses the in-memory FakeVault double (vault_testing.go role)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.vaultclient import ClientVaultClient
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.vault import (
    FakeVault,
    ServerVaultClient,
    VaultConfig,
    VaultError,
)
from nomad_tpu.structs import structs as s


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestFakeVault:
    def test_token_lifecycle(self):
        fv = FakeVault()
        out = fv.create_token(["read-db"], 60.0, {"AllocationID": "a1"})
        assert out["token"].startswith("s.") and out["accessor"].startswith("a.")
        assert fv.lookup_token(out["token"])["policies"] == ["read-db"]
        assert fv.renew_token(out["token"], 120.0) == 120.0
        fv.revoke_accessor(out["accessor"])
        assert fv.is_revoked(out["accessor"])
        with pytest.raises(VaultError):
            fv.lookup_token(out["token"])


class TestServerVaultClient:
    def make_alloc(self):
        job = mock.job()
        job.task_groups[0].tasks[0].vault = s.Vault(policies=["p1", "p2"])
        alloc = mock.alloc()
        alloc.job = job
        alloc.task_group = job.task_groups[0].name
        return alloc

    def test_derive_tokens_per_task(self):
        fv = FakeVault()
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv)
        alloc = self.make_alloc()
        out = vc.derive_token(alloc, ["web"])
        assert "web" in out and out["web"]["token"]
        rec = fv.lookup_token(out["web"]["token"])
        assert rec["policies"] == ["p1", "p2"]
        assert rec["metadata"]["AllocationID"] == alloc.id

    def test_derive_requires_vault_block(self):
        fv = FakeVault()
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv)
        alloc = mock.alloc()
        alloc.job = mock.job()  # no vault block
        alloc.task_group = alloc.job.task_groups[0].name
        with pytest.raises(VaultError):
            vc.derive_token(alloc, ["web"])

    def test_disabled_raises(self):
        vc = ServerVaultClient(VaultConfig(enabled=False))
        with pytest.raises(VaultError):
            vc.derive_token(self.make_alloc(), ["web"])


class TestRenewalHeap:
    def test_tokens_renewed_at_half_ttl(self):
        fv = FakeVault()
        out = fv.create_token(["p"], 0.4, {})
        cvc = ClientVaultClient(derive_fn=None, renew_fn=fv.renew_token)
        cvc.start()
        try:
            cvc.renew_token(out["token"], 0.4)
            assert wait_until(lambda: fv.renew_calls >= 2, 5.0), \
                "token was not renewed repeatedly"
        finally:
            cvc.stop()

    def test_zero_ttl_never_enters_heap(self):
        """ADVICE r5 vault.py:208: a missing lease_duration used to land
        a ttl=0.0 token in the renewal heap — an immediate, never-ending
        renewal churn loop.  ttl<=0 is now refused outright."""
        fv = FakeVault()
        out = fv.create_token(["p"], 60.0, {})
        cvc = ClientVaultClient(derive_fn=None, renew_fn=fv.renew_token)
        cvc.start()
        try:
            cvc.renew_token(out["token"], 0.0)
            cvc.renew_token(out["token"], -1.0)
            assert cvc.num_tracked() == 0
            time.sleep(0.3)
            assert fv.renew_calls == 0
        finally:
            cvc.stop()

    def test_unwrap_without_lease_falls_back_to_envelope_ttl(self):
        """ADVICE r5: when the unwrap response omits lease_duration, the
        derived-token dict falls back to the wrapped envelope's
        requested TTL instead of 0.0."""
        fv = FakeVault()

        def derive_fn(alloc_id, tasks):
            out = fv.create_token(["p"], 42.0, {}, wrap_ttl=60.0)
            return {"web": out}

        def unwrap_no_lease(wrapping_token):
            secret = fv.unwrap(wrapping_token)
            return {"token": secret["token"],
                    "accessor": secret["accessor"], "ttl": 0.0}

        cvc = ClientVaultClient(derive_fn=derive_fn, renew_fn=None,
                                unwrap_fn=unwrap_no_lease)
        out = cvc.derive_token("a1", ["web"])
        assert out["web"]["ttl"] == 42.0

    def test_stop_renew_stops(self):
        fv = FakeVault()
        out = fv.create_token(["p"], 0.2, {})
        cvc = ClientVaultClient(derive_fn=None, renew_fn=fv.renew_token)
        cvc.start()
        try:
            cvc.renew_token(out["token"], 0.2)
            wait_until(lambda: fv.renew_calls >= 1, 5.0)
            cvc.stop_renew_token(out["token"])
            count = fv.renew_calls
            time.sleep(0.5)
            assert fv.renew_calls <= count + 1  # at most one in-flight
            assert cvc.num_tracked() == 0
        finally:
            cvc.stop()


@pytest.mark.slow
class TestVaultEndToEnd:
    """Task gets a derived token; the accessor is registered through the
    log and revoked when the alloc stops (VERDICT r1 #7 'Done' criteria)."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        fv = FakeVault()
        srv = Server(ServerConfig(num_schedulers=1,
                                  vault=VaultConfig(enabled=True)),
                     vault_api=fv)
        srv.start()
        cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                           state_dir=str(tmp_path / "state"))
        client = Client(cfg, rpc=srv, vault_api=fv)
        client.start()
        yield srv, client, fv
        client.shutdown()
        srv.shutdown()

    def vault_job(self):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "60s"}
            t.resources.networks = []
            t.services = []
            t.vault = s.Vault(policies=["task-policy"])
        return job

    def test_token_derived_and_revoked_on_stop(self, cluster):
        srv, client, fv = cluster
        assert wait_until(lambda: srv.node_get(client.node.id) is not None
                          and srv.node_get(client.node.id).status == "ready")
        job = self.vault_job()
        srv.job_register(job)
        assert wait_until(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)))
        alloc = srv.job_allocations(job.id)[0]

        # Accessor registered via the log; token is live in Vault.
        assert wait_until(lambda: len(
            srv.state.vault_accessors_by_alloc(None, alloc.id)) == 1)
        acc = srv.state.vault_accessors_by_alloc(None, alloc.id)[0]
        assert acc.task == "web" and acc.node_id == client.node.id

        # The running task got the token in its secrets dir.
        runner = client.get_alloc_runner(alloc.id)
        token_path = os.path.join(runner.alloc_dir.task_dirs["web"].secrets_dir,
                                  "vault_token")
        assert wait_until(lambda: os.path.exists(token_path))
        token = open(token_path).read()
        assert fv.lookup_token(token)["policies"] == ["task-policy"]

        # Stopping the job drives the alloc terminal → revocation.
        srv.job_deregister(job.id, purge=False)
        assert wait_until(lambda: fv.is_revoked(acc.accessor), 20.0), \
            "accessor was not revoked after alloc stop"
        assert wait_until(lambda: not srv.state.vault_accessors_by_alloc(
            None, alloc.id), 10.0), "accessor row not deregistered"

    def test_leader_restore_revokes_stale_accessors(self, cluster):
        srv, client, fv = cluster
        from nomad_tpu.state.state_store import VaultAccessor
        from nomad_tpu.server.fsm import MessageType

        # A stale accessor whose alloc no longer exists (e.g. the previous
        # leader died mid-revocation, leader.go:221).
        out = fv.create_token(["p"], 60.0, {})
        srv.raft.apply(MessageType.VAULT_ACCESSOR_REGISTER, {"accessors": [
            VaultAccessor(accessor=out["accessor"], alloc_id="gone",
                          node_id="gone-node", task="t")]})
        srv._restore_revoking_accessors()
        assert wait_until(lambda: fv.is_revoked(out["accessor"]), 10.0)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestSelfTokenRenewal:
    """The server's own token renewal loop (vault.go:467-567
    renewalLoop/renew), driven tick-by-tick under a controlled clock."""

    def make(self, ttl=60.0):
        clock = FakeClock()
        fv = FakeVault(clock=clock)
        rec = fv.create_token(["root"], ttl, {})
        vc = ServerVaultClient(
            VaultConfig(enabled=True, token=rec["token"]), api=fv,
            clock=clock, rand=lambda: 0.5)
        vc.creation_ttl = ttl
        vc.last_renewed = clock()
        return clock, fv, vc

    def test_renew_scheduled_at_half_time_to_expiry(self):
        clock, fv, vc = self.make(ttl=60.0)
        delay = vc.renewal_tick()
        assert delay == pytest.approx(30.0)
        assert fv.renew_calls == 1
        # Later ticks keep renewing BEFORE expiry: delay is always half
        # the remaining lease, never past it.
        for _ in range(5):
            clock.advance(delay)
            remaining = vc.last_renewed + vc.creation_ttl - clock()
            assert remaining > 0, "renewal scheduled past expiry"
            delay = vc.renewal_tick()
            assert delay == pytest.approx(30.0)

    def test_error_backoff_ordering_and_cap(self):
        clock, fv, vc = self.make(ttl=200.0)
        # Break renewal: revoke the server token.
        fv.revoke_accessor(fv.tokens[vc.config.token]["accessor"])
        delays = []
        for _ in range(6):
            d = vc.renewal_tick()
            assert d is not None
            delays.append(d)
            clock.advance(min(d, 5.0))
        # 5 * 1.5 jitter, then *1.25 growth: strictly increasing until
        # the 30s cap region, and never more than half the remaining
        # lease (vault.go:498-537).
        assert delays[0] == pytest.approx(7.5)
        assert delays[1] == pytest.approx(7.5 * 1.25)
        for d in delays:
            remaining = vc.last_renewed + vc.creation_ttl - clock()
            assert d <= max(remaining / 2.0 + 5.0, 45.0)

    def test_gives_up_past_expiration(self):
        clock, fv, vc = self.make(ttl=10.0)
        fv.revoke_accessor(fv.tokens[vc.config.token]["accessor"])
        clock.advance(11.0)  # past the lease
        assert vc.renewal_tick() is None
        assert vc.connection_lost


class TestWrappedTokens:
    """Response-wrapped derive (vault.go:28 vaultTokenCreateTTL +
    getWrappingFn): single-use cubbyhole, short wrap TTL."""

    def make_alloc(self):
        job = mock.job()
        job.task_groups[0].tasks[0].vault = s.Vault(policies=["p1"])
        alloc = mock.alloc()
        alloc.job = job
        alloc.task_group = job.task_groups[0].name
        return alloc

    def test_wrapped_derive_and_single_use_unwrap(self):
        clock = FakeClock()
        fv = FakeVault(clock=clock)
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv,
                               clock=clock)
        out = vc.derive_token(self.make_alloc(), ["web"], wrapped=True)
        info = out["web"]
        assert "token" not in info, "raw secret leaked alongside wrapper"
        assert info["wrapped_token"].startswith("w.")
        # The accessor is known BEFORE distribution (failover revoke).
        assert info["accessor"].startswith("a.")
        secret = fv.unwrap(info["wrapped_token"])
        assert fv.lookup_token(secret["token"])["policies"] == ["p1"]
        with pytest.raises(VaultError):
            fv.unwrap(info["wrapped_token"])  # single use

    def test_wrapper_expires(self):
        clock = FakeClock()
        fv = FakeVault(clock=clock)
        out = fv.create_token(["p"], 60.0, {}, wrap_ttl=120.0)
        clock.advance(121.0)
        with pytest.raises(VaultError):
            fv.unwrap(out["wrapped_token"])

    def test_wrap_derived_tokens_flag_disables_wrapping(self):
        """VaultConfig.wrap_derived_tokens=False (ADVICE r5 server:1277):
        the server RPC hands out PLAIN tokens again, so non-embedded
        clients without a vault_addr keep working across the upgrade."""
        fv = FakeVault()
        for flag, want_plain in ((False, True), (True, False)):
            srv = Server(ServerConfig(
                num_schedulers=0,
                vault=VaultConfig(enabled=True,
                                  wrap_derived_tokens=flag)),
                vault_api=fv)
            srv.start()
            try:
                assert wait_until(srv.is_leader)
                job = mock.job()
                job.task_groups[0].tasks[0].vault = s.Vault(policies=["p1"])
                alloc = mock.alloc()
                alloc.job = job
                alloc.job_id = job.id
                alloc.task_group = job.task_groups[0].name
                srv.state.upsert_job(srv.raft.applied_index() + 1, job)
                srv.state.upsert_allocs(srv.raft.applied_index() + 2,
                                        [alloc])
                out = srv.derive_vault_token(alloc.id, ["web"])
                info = out["web"]
                assert ("token" in info) == want_plain, (flag, info)
                assert ("wrapped_token" in info) == (not want_plain)
                # Revocation accessors register either way.
                assert wait_until(lambda: len(
                    srv.state.vault_accessors_by_alloc(
                        None, alloc.id)) == 1)
            finally:
                srv.shutdown()


class TestRevocationRetry:
    """storeForRevocation + revokeDaemon (vault.go:1027, 1104): failed
    revokes queue and retry until the token TTL; deactivation clears."""

    def test_retry_until_success(self):
        clock = FakeClock()
        fv = FakeVault(clock=clock)
        rec = fv.create_token(["p"], 60.0, {})
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv,
                               clock=clock)
        fv.fail_revokes = 1
        assert vc.revoke_accessors([rec["accessor"]]) == []
        vc.store_for_revocation([rec["accessor"]], ttl=60.0)
        assert vc.num_revoking() == 1
        fv.fail_revokes = 1
        assert vc.tick_revocations() == []      # still failing
        assert vc.num_revoking() == 1
        assert vc.tick_revocations() == [rec["accessor"]]
        assert fv.is_revoked(rec["accessor"])
        assert vc.num_revoking() == 0

    def test_queue_drops_past_token_ttl(self):
        clock = FakeClock()
        fv = FakeVault(clock=clock)
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv,
                               clock=clock)
        vc.store_for_revocation(["a.dead"], ttl=30.0)
        clock.advance(31.0)
        assert vc.tick_revocations() == []
        assert vc.num_revoking() == 0           # dropped, not revoked
        assert not fv.is_revoked("a.dead")

    def test_deactivation_clears_queue(self):
        fv = FakeVault()
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv)
        vc.store_for_revocation(["a.x"], ttl=60.0)
        vc.set_active(False)                    # another leader takes over
        assert vc.num_revoking() == 0
        assert vc.tick_revocations() == []


@pytest.mark.slow
class TestVaultFailureModes:
    """revoke-on-node-down and restore-after-failover (VERDICT r4 #7)."""

    def test_revoke_on_node_down(self, tmp_path):
        """Node goes down → its allocs are lost (terminal) → the leader
        revokes every accessor derived for them (vault.go RevokeTokens
        via the alloc-terminal hook; leader.go restore checks nodes)."""
        fv = FakeVault()
        srv = Server(ServerConfig(num_schedulers=1,
                                  vault=VaultConfig(enabled=True)),
                     vault_api=fv)
        srv.start()
        cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                           state_dir=str(tmp_path / "state"))
        client = Client(cfg, rpc=srv, vault_api=fv)
        client.start()
        try:
            assert wait_until(
                lambda: srv.node_get(client.node.id) is not None
                and srv.node_get(client.node.id).status == "ready")
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
            for t in tg.tasks:
                t.driver = "mock_driver"
                t.config = {"run_for": "60s"}
                t.resources.networks = []
                t.services = []
                t.vault = s.Vault(policies=["task-policy"])
            srv.job_register(job)
            assert wait_until(lambda: any(
                a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
                for a in srv.job_allocations(job.id)))
            alloc = srv.job_allocations(job.id)[0]
            assert wait_until(lambda: len(
                srv.state.vault_accessors_by_alloc(None, alloc.id)) == 1)
            acc = srv.state.vault_accessors_by_alloc(None, alloc.id)[0]

            # Stop the client's heartbeats, then force the node down.
            client.shutdown()
            srv.node_update_status(client.node.id, s.NODE_STATUS_DOWN)
            assert wait_until(lambda: fv.is_revoked(acc.accessor), 20.0), \
                "accessor not revoked after node down"
        finally:
            srv.shutdown()

    def test_restore_after_failover(self, tmp_path):
        """A stale accessor registered through the log is revoked by the
        NEW leader after the old one dies (leader.go:219
        restoreRevokingAccessors on leadership establishment)."""
        from nomad_tpu.server.fsm import MessageType
        from nomad_tpu.state.state_store import VaultAccessor

        fv = FakeVault()
        servers = []
        first_addr = None
        for i in range(3):
            cfg = ServerConfig(
                node_name=f"vault-s{i+1}",
                data_dir=str(tmp_path / f"s{i+1}"),
                enable_rpc=True, bootstrap_expect=3,
                start_join=[first_addr] if first_addr else [],
                num_schedulers=0,
                vault=VaultConfig(enabled=True))
            srv = Server(cfg, vault_api=fv)
            if first_addr is None:
                first_addr = srv.config.rpc_advertise
            servers.append(srv)
        for srv in servers:
            srv.start()
        try:
            assert wait_until(lambda: any(
                srv.is_leader() for srv in servers), 30.0)
            leader = next(srv for srv in servers if srv.is_leader())

            # Register an accessor whose alloc does not exist — as if the
            # old leader died between minting and revoking.
            out = fv.create_token(["p"], 3600.0, {})
            leader.raft.apply(
                MessageType.VAULT_ACCESSOR_REGISTER,
                {"accessors": [VaultAccessor(
                    accessor=out["accessor"], alloc_id="gone",
                    node_id="gone-node", task="t")]})
            followers = [srv for srv in servers if srv is not leader]
            assert wait_until(lambda: all(
                len(srv.state.vault_accessors(None)) == 1
                for srv in followers), 10.0)

            leader.shutdown()
            assert wait_until(lambda: any(
                srv.is_leader() for srv in followers), 30.0)
            # The new leader's establish hook sweeps and revokes.
            assert wait_until(lambda: fv.is_revoked(out["accessor"]),
                              20.0), "new leader did not revoke"
        finally:
            for srv in servers:
                srv.shutdown()
