"""Vault subsystem tests (reference: nomad/vault.go:234-1218 server client,
client/vaultclient renewal heap, node_endpoint.go DeriveVaultToken).
Uses the in-memory FakeVault double (vault_testing.go role)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.vaultclient import ClientVaultClient
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.vault import (
    FakeVault,
    ServerVaultClient,
    VaultConfig,
    VaultError,
)
from nomad_tpu.structs import structs as s


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestFakeVault:
    def test_token_lifecycle(self):
        fv = FakeVault()
        out = fv.create_token(["read-db"], 60.0, {"AllocationID": "a1"})
        assert out["token"].startswith("s.") and out["accessor"].startswith("a.")
        assert fv.lookup_token(out["token"])["policies"] == ["read-db"]
        assert fv.renew_token(out["token"], 120.0) == 120.0
        fv.revoke_accessor(out["accessor"])
        assert fv.is_revoked(out["accessor"])
        with pytest.raises(VaultError):
            fv.lookup_token(out["token"])


class TestServerVaultClient:
    def make_alloc(self):
        job = mock.job()
        job.task_groups[0].tasks[0].vault = s.Vault(policies=["p1", "p2"])
        alloc = mock.alloc()
        alloc.job = job
        alloc.task_group = job.task_groups[0].name
        return alloc

    def test_derive_tokens_per_task(self):
        fv = FakeVault()
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv)
        alloc = self.make_alloc()
        out = vc.derive_token(alloc, ["web"])
        assert "web" in out and out["web"]["token"]
        rec = fv.lookup_token(out["web"]["token"])
        assert rec["policies"] == ["p1", "p2"]
        assert rec["metadata"]["AllocationID"] == alloc.id

    def test_derive_requires_vault_block(self):
        fv = FakeVault()
        vc = ServerVaultClient(VaultConfig(enabled=True), api=fv)
        alloc = mock.alloc()
        alloc.job = mock.job()  # no vault block
        alloc.task_group = alloc.job.task_groups[0].name
        with pytest.raises(VaultError):
            vc.derive_token(alloc, ["web"])

    def test_disabled_raises(self):
        vc = ServerVaultClient(VaultConfig(enabled=False))
        with pytest.raises(VaultError):
            vc.derive_token(self.make_alloc(), ["web"])


class TestRenewalHeap:
    def test_tokens_renewed_at_half_ttl(self):
        fv = FakeVault()
        out = fv.create_token(["p"], 0.4, {})
        cvc = ClientVaultClient(derive_fn=None, renew_fn=fv.renew_token)
        cvc.start()
        try:
            cvc.renew_token(out["token"], 0.4)
            assert wait_until(lambda: fv.renew_calls >= 2, 5.0), \
                "token was not renewed repeatedly"
        finally:
            cvc.stop()

    def test_stop_renew_stops(self):
        fv = FakeVault()
        out = fv.create_token(["p"], 0.2, {})
        cvc = ClientVaultClient(derive_fn=None, renew_fn=fv.renew_token)
        cvc.start()
        try:
            cvc.renew_token(out["token"], 0.2)
            wait_until(lambda: fv.renew_calls >= 1, 5.0)
            cvc.stop_renew_token(out["token"])
            count = fv.renew_calls
            time.sleep(0.5)
            assert fv.renew_calls <= count + 1  # at most one in-flight
            assert cvc.num_tracked() == 0
        finally:
            cvc.stop()


class TestVaultEndToEnd:
    """Task gets a derived token; the accessor is registered through the
    log and revoked when the alloc stops (VERDICT r1 #7 'Done' criteria)."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        fv = FakeVault()
        srv = Server(ServerConfig(num_schedulers=1,
                                  vault=VaultConfig(enabled=True)),
                     vault_api=fv)
        srv.start()
        cfg = ClientConfig(alloc_dir=str(tmp_path / "allocs"),
                           state_dir=str(tmp_path / "state"))
        client = Client(cfg, rpc=srv, vault_api=fv)
        client.start()
        yield srv, client, fv
        client.shutdown()
        srv.shutdown()

    def vault_job(self):
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
        for t in tg.tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "60s"}
            t.resources.networks = []
            t.services = []
            t.vault = s.Vault(policies=["task-policy"])
        return job

    def test_token_derived_and_revoked_on_stop(self, cluster):
        srv, client, fv = cluster
        assert wait_until(lambda: srv.node_get(client.node.id) is not None
                          and srv.node_get(client.node.id).status == "ready")
        job = self.vault_job()
        srv.job_register(job)
        assert wait_until(lambda: any(
            a.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
            for a in srv.job_allocations(job.id)))
        alloc = srv.job_allocations(job.id)[0]

        # Accessor registered via the log; token is live in Vault.
        assert wait_until(lambda: len(
            srv.state.vault_accessors_by_alloc(None, alloc.id)) == 1)
        acc = srv.state.vault_accessors_by_alloc(None, alloc.id)[0]
        assert acc.task == "web" and acc.node_id == client.node.id

        # The running task got the token in its secrets dir.
        runner = client.get_alloc_runner(alloc.id)
        token_path = os.path.join(runner.alloc_dir.task_dirs["web"].secrets_dir,
                                  "vault_token")
        assert wait_until(lambda: os.path.exists(token_path))
        token = open(token_path).read()
        assert fv.lookup_token(token)["policies"] == ["task-policy"]

        # Stopping the job drives the alloc terminal → revocation.
        srv.job_deregister(job.id, purge=False)
        assert wait_until(lambda: fv.is_revoked(acc.accessor), 20.0), \
            "accessor was not revoked after alloc stop"
        assert wait_until(lambda: not srv.state.vault_accessors_by_alloc(
            None, alloc.id), 10.0), "accessor row not deregistered"

    def test_leader_restore_revokes_stale_accessors(self, cluster):
        srv, client, fv = cluster
        from nomad_tpu.state.state_store import VaultAccessor
        from nomad_tpu.server.fsm import MessageType

        # A stale accessor whose alloc no longer exists (e.g. the previous
        # leader died mid-revocation, leader.go:221).
        out = fv.create_token(["p"], 60.0, {})
        srv.raft.apply(MessageType.VAULT_ACCESSOR_REGISTER, {"accessors": [
            VaultAccessor(accessor=out["accessor"], alloc_id="gone",
                          node_id="gone-node", task="t")]})
        srv._restore_revoking_accessors()
        assert wait_until(lambda: fv.is_revoked(out["accessor"]), 10.0)
