"""L1 tests: state store (reference: nomad/state/state_store_test.go)."""
import threading
import time

from nomad_tpu import mock
from nomad_tpu.state import PeriodicLaunch, StateStore, WatchSet
from nomad_tpu.structs import structs as s


def test_upsert_node_indexes():
    store = StateStore()
    n = mock.node()
    store.upsert_node(1000, n)
    out = store.node_by_id(None, n.id)
    assert out.create_index == 1000
    assert out.modify_index == 1000
    # update preserves create index
    n2 = out.copy()
    n2.name = "renamed"
    store.upsert_node(1001, n2)
    out = store.node_by_id(None, n.id)
    assert out.create_index == 1000
    assert out.modify_index == 1001
    assert store.table_index("nodes") == 1001


def test_node_status_and_drain():
    store = StateStore()
    n = mock.node()
    store.upsert_node(1, n)
    store.update_node_status(2, n.id, s.NODE_STATUS_DOWN)
    assert store.node_by_id(None, n.id).status == s.NODE_STATUS_DOWN
    store.update_node_drain(3, n.id, True)
    assert store.node_by_id(None, n.id).drain


def test_upsert_job_versions_and_summary():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1000, j)
    out = store.job_by_id(None, j.id)
    assert out.version == 0
    assert out.status == s.JOB_STATUS_PENDING
    summary = store.job_summary_by_id(None, j.id)
    assert "web" in summary.summary
    # re-register bumps version, keeps create index
    j2 = out.copy()
    j2.priority = 70
    store.upsert_job(1001, j2)
    out2 = store.job_by_id(None, j.id)
    assert out2.version == 1
    assert out2.create_index == 1000
    versions = store.job_versions_by_id(None, j.id)
    assert [v.version for v in versions] == [1, 0]
    assert store.job_by_id_and_version(None, j.id, 0).priority == 50


def test_delete_job():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    store.delete_job(2, j.id)
    assert store.job_by_id(None, j.id) is None
    assert store.job_summary_by_id(None, j.id) is None


def test_upsert_evals_sets_job_pending_and_queued():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    ev = mock.eval()
    ev.job_id = j.id
    ev.queued_allocations = {"web": 4}
    store.upsert_evals(2, [ev])
    assert store.eval_by_id(None, ev.id).create_index == 2
    assert store.job_by_id(None, j.id).status == s.JOB_STATUS_PENDING
    assert store.job_summary_by_id(None, j.id).summary["web"].queued == 4
    assert store.evals_by_job(None, j.id)[0].id == ev.id


def test_successful_eval_cancels_blocked():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    blocked = mock.eval()
    blocked.job_id = j.id
    blocked.status = s.EVAL_STATUS_BLOCKED
    store.upsert_evals(2, [blocked])
    done = mock.eval()
    done.job_id = j.id
    done.status = s.EVAL_STATUS_COMPLETE
    store.upsert_evals(3, [done])
    assert store.eval_by_id(None, blocked.id).status == s.EVAL_STATUS_CANCELLED


def test_upsert_allocs_and_queries():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    a = mock.alloc()
    a.job = store.job_by_id(None, j.id)
    a.job_id = j.id
    store.upsert_allocs(2, [a])
    assert store.alloc_by_id(None, a.id).create_index == 2
    assert [x.id for x in store.allocs_by_node(None, a.node_id)] == [a.id]
    assert [x.id for x in store.allocs_by_job(None, j.id)] == [a.id]
    assert [x.id for x in store.allocs_by_eval(None, a.eval_id)] == [a.id]
    # non-terminal alloc → job running
    assert store.job_by_id(None, j.id).status == s.JOB_STATUS_RUNNING
    # terminal filter
    assert store.allocs_by_node_terminal(None, a.node_id, True) == []
    assert len(store.allocs_by_node_terminal(None, a.node_id, False)) == 1


def test_update_allocs_from_client_summary_transitions():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    a = mock.alloc()
    a.job = store.job_by_id(None, j.id)
    a.job_id = j.id
    store.upsert_allocs(2, [a])
    summary = store.job_summary_by_id(None, j.id)
    assert summary.summary["web"].starting == 1

    update = a.copy()
    update.client_status = s.ALLOC_CLIENT_STATUS_RUNNING
    store.update_allocs_from_client(3, [update])
    stored = store.alloc_by_id(None, a.id)
    assert stored.client_status == s.ALLOC_CLIENT_STATUS_RUNNING
    summary = store.job_summary_by_id(None, j.id)
    assert summary.summary["web"].running == 1
    assert summary.summary["web"].starting == 0

    update2 = stored.copy()
    update2.client_status = s.ALLOC_CLIENT_STATUS_COMPLETE
    store.update_allocs_from_client(4, [update2])
    summary = store.job_summary_by_id(None, j.id)
    assert summary.summary["web"].complete == 1
    assert summary.summary["web"].running == 0


def test_upsert_allocs_preserves_client_fields():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    a = mock.alloc()
    a.job = store.job_by_id(None, j.id)
    a.job_id = j.id
    store.upsert_allocs(2, [a])
    upd = a.copy()
    upd.client_status = s.ALLOC_CLIENT_STATUS_RUNNING
    store.update_allocs_from_client(3, [upd])
    # server-side re-upsert (e.g. desired status change) must not clobber
    # the client-authoritative status
    server_view = a.copy()
    server_view.desired_status = s.ALLOC_DESIRED_STATUS_STOP
    store.upsert_allocs(4, [server_view])
    stored = store.alloc_by_id(None, a.id)
    assert stored.desired_status == s.ALLOC_DESIRED_STATUS_STOP
    assert stored.client_status == s.ALLOC_CLIENT_STATUS_RUNNING


def test_snapshot_isolation():
    store = StateStore()
    n = mock.node()
    store.upsert_node(1, n)
    snap = store.snapshot()
    n2 = mock.node()
    store.upsert_node(2, n2)
    assert len(store.nodes(None)) == 2
    assert len(snap.nodes(None)) == 1
    # writes to the snapshot stay local (plan-apply optimistic application)
    snap.upsert_node(3, mock.node())
    assert len(snap.nodes(None)) == 2
    assert len(store.nodes(None)) == 2
    assert store.latest_index() == 2


def test_upsert_plan_results_builds_resources():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    a = mock.alloc()
    a.job = None
    a.job_id = j.id
    a.resources = None  # plan allocs carry only task resources
    store.upsert_plan_results(2, store.job_by_id(None, j.id), [a])
    stored = store.alloc_by_id(None, a.id)
    assert stored.job is not None
    assert stored.resources.cpu == 500
    assert stored.resources.disk_mb == 150  # shared resources folded in


def test_periodic_launch_table():
    store = StateStore()
    launch = PeriodicLaunch(id="job1", launch=12345.0)
    store.upsert_periodic_launch(5, launch)
    out = store.periodic_launch_by_id(None, "job1")
    assert out.launch == 12345.0
    assert out.create_index == 5
    store.delete_periodic_launch(6, "job1")
    assert store.periodic_launch_by_id(None, "job1") is None


def test_delete_eval_and_allocs():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    ev = mock.eval()
    ev.job_id = j.id
    store.upsert_evals(2, [ev])
    a = mock.alloc()
    a.job_id = j.id
    a.eval_id = ev.id
    store.upsert_allocs(3, [a])
    store.delete_eval(4, [ev.id], [a.id])
    assert store.eval_by_id(None, ev.id) is None
    assert store.alloc_by_id(None, a.id) is None
    # eval_delete=True with no remaining evals/allocs → job dead
    assert store.job_by_id(None, j.id).status == s.JOB_STATUS_DEAD


def test_persist_restore_roundtrip():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    n = mock.node()
    store.upsert_node(2, n)
    a = mock.alloc()
    a.job_id = j.id
    store.upsert_allocs(3, [a])
    blob = store.persist()
    restored = StateStore.restore(blob)
    assert restored.job_by_id(None, j.id).id == j.id
    assert restored.node_by_id(None, n.id).id == n.id
    assert [x.id for x in restored.allocs_by_job(None, j.id, all_allocs=True)] == [a.id]
    assert restored.latest_index() == 3


def test_blocking_watchset():
    store = StateStore()
    ws = WatchSet()
    store.nodes(ws)
    fired = []

    def waiter():
        timed_out = ws.watch(timeout=5.0)
        fired.append(timed_out)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    store.upsert_node(1, mock.node())
    t.join(timeout=5.0)
    assert fired == [False]  # woke due to write, not timeout


def test_watchset_timeout():
    store = StateStore()
    ws = WatchSet()
    store.jobs(ws)
    assert ws.watch(timeout=0.05) is True


def test_reconcile_job_summaries():
    store = StateStore()
    j = mock.job()
    store.upsert_job(1, j)
    a = mock.alloc()
    a.job = store.job_by_id(None, j.id)
    a.job_id = j.id
    a.client_status = s.ALLOC_CLIENT_STATUS_RUNNING
    store.upsert_allocs(2, [a])
    # clobber summary then rebuild
    store.job_summary_table[j.id] = s.JobSummary(job_id=j.id)
    store.reconcile_job_summaries(3)
    assert store.job_summary_by_id(None, j.id).summary["web"].running == 1


def test_ready_nodes_memo_shared_across_snapshots():
    """ISSUE 14: the ready_nodes_in_dcs memo dict is SHARED between a
    store and every snapshot cut from the same node-table state — one
    O(cluster) ready walk warms the whole steady stream of per-batch
    snapshots — and any node write invalidates only the writer's view."""
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs

    store = StateStore()
    for i in range(6):
        n = mock.node()
        n.id = f"node-{i}"
        n.datacenter = "dc1"
        store.upsert_node(i + 1, n)

    s1 = store.snapshot()
    out1, dcs1 = ready_nodes_in_dcs(s1, ["dc1"])
    assert len(out1) == 6 and dcs1 == {"dc1": 6}
    s2 = store.snapshot()
    # Same shared dict, already warm — and it serves the same answer.
    assert s2._ready_nodes_cache is s1._ready_nodes_cache
    assert ("dc1",) in s2._ready_nodes_cache
    out2, _ = ready_nodes_in_dcs(s2, ["dc1"])
    assert [n.id for n in out2] == [n.id for n in out1]

    # A node write on the base severs only the base's reference: the
    # next snapshot recomputes, frozen older snapshots stay warm+correct.
    n = mock.node()
    n.id = "node-6"
    n.datacenter = "dc1"
    store.upsert_node(50, n)
    s3 = store.snapshot()
    assert s3._ready_nodes_cache is not s1._ready_nodes_cache
    out3, _ = ready_nodes_in_dcs(s3, ["dc1"])
    assert len(out3) == 7
    assert len(ready_nodes_in_dcs(s1, ["dc1"])[0]) == 6

    # A hypothetical write on a SNAPSHOT (dry-run world) diverges that
    # snapshot only; the shared memo still serves its siblings.
    s4 = store.snapshot()
    ready_nodes_in_dcs(s4, ["dc1"])
    extra = mock.node()
    extra.datacenter = "dc1"
    s4.upsert_node(60, extra)
    assert len(ready_nodes_in_dcs(s4, ["dc1"])[0]) == 8
    assert len(ready_nodes_in_dcs(s3, ["dc1"])[0]) == 7

    # Returned lists are copies — mutating one can't poison the memo.
    got, _ = ready_nodes_in_dcs(s3, ["dc1"])
    got.clear()
    assert len(ready_nodes_in_dcs(s3, ["dc1"])[0]) == 7
