"""Dedicated coverage for server/heartbeat.py (previously untested):
TTL scaling with fleet size, expiry → on_expire, timer lifecycle, and
the end-to-end expiry → node down → non-terminal allocs → lost chain.
"""
import threading
import time

import pytest

from nomad_tpu import fault, mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.heartbeat import HeartbeatTimers
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    fault.disarm()


class TestTTLScaling:
    def test_small_fleet_gets_min_ttl(self):
        h = HeartbeatTimers(on_expire=lambda nid: None, min_ttl=10.0,
                            max_per_second=50.0, grace=10.0,
                            ttl_jitter=0.0)
        h.set_enabled(True)
        try:
            assert h.reset_heartbeat_timer("n1") == 10.0
            assert h.reset_heartbeat_timer("n2") == 10.0
        finally:
            h.set_enabled(False)

    def test_ttl_scales_with_fleet_size(self):
        """ttl = max(min_ttl, nodes / max_heartbeats_per_second)
        (config.go:185-197): a 500-node fleet at 50 hb/s spreads
        heartbeats over ≥10s each."""
        h = HeartbeatTimers(on_expire=lambda nid: None, min_ttl=1.0,
                            max_per_second=10.0, grace=60.0,
                            ttl_jitter=0.0)
        h.set_enabled(True)
        try:
            for i in range(100):
                h.reset_heartbeat_timer(f"node-{i}")
            assert h.active() == 100
            # 100 tracked timers / 10 per second ⇒ 10s TTL
            assert h.reset_heartbeat_timer("node-next") == pytest.approx(10.0)
            # fleet shrink ⇒ TTL shrinks back to the min_ttl floor
            for i in range(100):
                h.clear_heartbeat_timer(f"node-{i}")
            assert h.reset_heartbeat_timer("node-next") == pytest.approx(1.0)
        finally:
            h.set_enabled(False)

    def test_initial_ttl_jitter_disperses_renewals(self):
        """Thundering-herd regression (ISSUE 7 satellite): a fleet
        registered in one burst must NOT be granted identical TTLs —
        identical grants phase-lock every client's renewal onto the same
        beat forever.  With the default jitter the granted TTLs (and so
        the renewal arrival times) spread over a band ≥ half the
        configured jitter width, and every grant stays within
        [ttl, ttl·(1+jitter)] so expiry timing guarantees hold."""
        import random

        h = HeartbeatTimers(on_expire=lambda nid: None, min_ttl=10.0,
                            max_per_second=50.0, grace=10.0,
                            ttl_jitter=0.1, rng=random.Random(42))
        h.set_enabled(True)
        try:
            ttls = [h.reset_heartbeat_timer(f"burst-{i}")
                    for i in range(200)]
        finally:
            h.set_enabled(False)
        assert all(10.0 <= t <= 10.0 * 1.1 + 1e-9 for t in ttls)
        # Dispersed, not clustered: the spread covers most of the jitter
        # band and no single value dominates.
        assert max(ttls) - min(ttls) >= 10.0 * 0.1 * 0.5
        assert len({round(t, 3) for t in ttls}) > 150

    def test_disabled_grants_min_ttl_without_tracking(self):
        h = HeartbeatTimers(on_expire=lambda nid: None, min_ttl=3.0)
        assert h.reset_heartbeat_timer("n1") == 3.0
        assert h.active() == 0


class TestExpiry:
    def test_expiry_fires_on_expire_once(self):
        expired = []
        done = threading.Event()

        def on_expire(nid):
            expired.append(nid)
            done.set()

        h = HeartbeatTimers(on_expire=on_expire, min_ttl=0.05,
                            max_per_second=1000.0, grace=0.05)
        h.set_enabled(True)
        try:
            h.reset_heartbeat_timer("n1")
            assert done.wait(5.0)
            time.sleep(0.15)  # no double fire
            assert expired == ["n1"]
            assert h.active() == 0
        finally:
            h.set_enabled(False)

    def test_reset_before_expiry_keeps_node_alive(self):
        expired = []
        h = HeartbeatTimers(on_expire=expired.append, min_ttl=0.15,
                            max_per_second=1000.0, grace=0.05)
        h.set_enabled(True)
        try:
            h.reset_heartbeat_timer("n1")
            for _ in range(5):
                time.sleep(0.05)
                h.reset_heartbeat_timer("n1")  # keep beating at TTL/3
            assert expired == []
            assert h.active() == 1
        finally:
            h.set_enabled(False)

    def test_clear_cancels_pending_expiry(self):
        expired = []
        h = HeartbeatTimers(on_expire=expired.append, min_ttl=0.05,
                            max_per_second=1000.0, grace=0.02)
        h.set_enabled(True)
        try:
            h.reset_heartbeat_timer("n1")
            h.clear_heartbeat_timer("n1")
            time.sleep(0.2)
            assert expired == []
        finally:
            h.set_enabled(False)

    def test_disable_cancels_all_timers(self):
        expired = []
        h = HeartbeatTimers(on_expire=expired.append, min_ttl=0.05,
                            max_per_second=1000.0, grace=0.02)
        h.set_enabled(True)
        for i in range(5):
            h.reset_heartbeat_timer(f"n{i}")
        h.set_enabled(False)
        assert h.active() == 0
        time.sleep(0.2)
        assert expired == []

    def test_on_expire_exception_does_not_propagate(self):
        done = threading.Event()

        def bad_hook(nid):
            done.set()
            raise RuntimeError("hook blew up")

        h = HeartbeatTimers(on_expire=bad_hook, min_ttl=0.05,
                            max_per_second=1000.0, grace=0.02)
        h.set_enabled(True)
        try:
            h.reset_heartbeat_timer("n1")
            assert done.wait(5.0)  # fired, exception swallowed + logged
        finally:
            h.set_enabled(False)

    def test_fault_point_drop_suppresses_reset(self):
        """An armed ``heartbeat.deliver`` drop swallows the TTL reset:
        the previously started timer keeps running and expires."""
        expired = []
        done = threading.Event()
        h = HeartbeatTimers(
            on_expire=lambda nid: (expired.append(nid), done.set()),
            min_ttl=0.1, max_per_second=1000.0, grace=0.05)
        h.set_enabled(True)
        try:
            h.reset_heartbeat_timer("n1")
            fault.arm([{"point": "heartbeat.deliver", "action": "drop",
                        "match": {"node_id": "n1"}}])
            # "heartbeats" keep arriving but delivery is dropped
            for _ in range(6):
                h.reset_heartbeat_timer("n1")
                time.sleep(0.05)
            assert done.wait(5.0)
            assert expired == ["n1"]
        finally:
            h.set_enabled(False)


class TestEndToEndExpiry:
    def test_expiry_node_down_allocs_lost(self):
        """TTL expiry → on_expire → node down → node-update eval →
        non-terminal allocs transition to lost (the full chain the
        81-line module anchors)."""
        srv = Server(ServerConfig(num_schedulers=1, min_heartbeat_ttl=0.3,
                                  max_heartbeats_per_second=1000.0))
        srv.heartbeat.grace = 0.2
        srv.start()
        try:
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            srv.node_register(node)
            srv.node_update_status(node.id, s.NODE_STATUS_READY)

            job = mock.job()
            job.task_groups[0].count = 2
            for t in job.task_groups[0].tasks:
                t.resources.networks = []
            srv.job_register(job)
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job.id, True)
                if not a.terminal_status()]) == 2, timeout=60.0)

            # stop heartbeating: TTL 0.3 + grace 0.2 ⇒ down ≈ 0.5s later
            assert wait_until(
                lambda: srv.state.node_by_id(None, node.id).status
                == s.NODE_STATUS_DOWN, timeout=10.0)
            assert wait_until(lambda: len([
                a for a in srv.state.allocs_by_job(None, job.id, True)
                if a.client_status == s.ALLOC_CLIENT_STATUS_LOST]) == 2,
                timeout=30.0)
            # desired status flips to stop for the lost copies
            lost = [a for a in srv.state.allocs_by_job(None, job.id, True)
                    if a.client_status == s.ALLOC_CLIENT_STATUS_LOST]
            assert all(a.desired_status == s.ALLOC_DESIRED_STATUS_STOP
                       for a in lost)
        finally:
            srv.shutdown()
