"""CLI command tests against a live dev agent
(reference: command/*_test.go)."""

import io
import time

import pytest

import conftest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.cli import main

JOBFILE = '''
job "cli-demo" {
  datacenters = ["dc1"]

  group "web" {
    count = 2

    task "srv" {
      driver = "mock_driver"
      config {
        run_for = "60s"
      }
      resources {
        cpu    = 20
        memory = 16
      }
    }
  }
}
'''


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    cfg = conftest.dev_test_config()
    tmp = tmp_path_factory.mktemp("cli-agent")
    cfg.client.alloc_dir = str(tmp / "allocs")
    cfg.client.state_dir = str(tmp / "state")
    a = Agent(cfg)
    a.start()
    # wait for the client node to register + go ready before scheduling
    assert wait_until(
        lambda: any(n.status == "ready" for n in a.server.state.nodes(None)))
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def addr(agent):
    return agent.http.address


@pytest.fixture(scope="module")
def jobfile(tmp_path_factory):
    p = tmp_path_factory.mktemp("jobs") / "demo.nomad"
    p.write_text(JOBFILE)
    return str(p)


def run_cli(args):
    out = io.StringIO()
    code = main(args, out=out)
    return code, out.getvalue()


class TestJobLifecycle:
    def test_validate(self, addr, jobfile):
        code, out = run_cli(["validate", "-address", addr, jobfile])
        assert code == 0
        assert "validation successful" in out

    def test_plan_new_job(self, addr, jobfile):
        code, out = run_cli(["plan", "-address", addr, jobfile])
        assert code == 1  # changes present -> exit 1 like the reference
        assert "Job: 'cli-demo'" in out or "Job: \"cli-demo\"" in out.replace(
            "'", '"')
        assert "2 create" in out
        assert "Job Modify Index: 0" in out

    def test_run_and_monitor(self, addr, jobfile):
        code, out = run_cli(["run", "-address", addr, jobfile])
        assert code == 0, out
        assert "Monitoring evaluation" in out
        assert 'finished with status "complete"' in out
        assert out.count("Allocation") >= 2

    def test_status_list_and_detail(self, addr):
        code, out = run_cli(["status", "-address", addr])
        assert code == 0
        assert "cli-demo" in out

        code, out = run_cli(["status", "-address", addr, "cli-demo"])
        assert code == 0
        assert "ID" in out and "cli-demo" in out
        assert "Summary" in out
        assert "Allocations" in out

    def test_inspect(self, addr):
        code, out = run_cli(["inspect", "-address", addr, "cli-demo"])
        assert code == 0
        assert '"ID": "cli-demo"' in out

    def test_alloc_and_eval_status(self, addr):
        from nomad_tpu.api import NomadAPI
        api = NomadAPI(addr)
        allocs, _ = api.jobs.allocations("cli-demo")
        assert allocs
        alloc_id = allocs[0]["ID"]
        code, out = run_cli(["alloc-status", "-address", addr, alloc_id])
        assert code == 0
        assert alloc_id in out
        assert "cli-demo" in out

        eval_id = allocs[0]["EvalID"]
        code, out = run_cli(["eval-status", "-address", addr, eval_id])
        assert code == 0
        assert "complete" in out

    def test_plan_after_run_no_changes_exit0(self, addr, jobfile):
        # Re-planning an unchanged job bumps JobModifyIndex in the plan
        # snapshot, so existing allocs surface as in-place updates — the
        # reference behaves identically (diffAllocs JobModifyIndex check).
        code, out = run_cli(["plan", "-address", addr, jobfile])
        assert code == 0
        assert "2 in-place update" in out

    def test_stop(self, addr, jobfile):
        code, out = run_cli(["stop", "-address", addr, "-detach", "cli-demo"])
        assert code == 0
        code, out = run_cli(["status", "-address", addr, "cli-demo"])
        assert code == 1
        assert "No job(s)" in out


class TestNodeCommands:
    def test_node_status_list(self, addr):
        code, out = run_cli(["node-status", "-address", addr])
        assert code == 0
        assert "ready" in out

    def test_node_status_detail(self, addr):
        from nomad_tpu.api import NomadAPI
        nodes, _ = NomadAPI(addr).nodes.list()
        node_id = nodes[0]["ID"]
        code, out = run_cli(["node-status", "-address", addr, node_id[:8]])
        assert code == 0
        assert "Allocated Resources" in out

    def test_node_drain_requires_flag(self, addr):
        from nomad_tpu.api import NomadAPI
        nodes, _ = NomadAPI(addr).nodes.list()
        node_id = nodes[0]["ID"]
        code, out = run_cli(["node-drain", "-address", addr, node_id])
        assert code == 1
        code, out = run_cli(
            ["node-drain", "-address", addr, "-enable", node_id])
        assert code == 0
        code, out = run_cli(
            ["node-drain", "-address", addr, "-disable", node_id])
        assert code == 0


class TestMiscCommands:
    def test_server_members(self, addr):
        code, out = run_cli(["server-members", "-address", addr])
        assert code == 0
        assert "alive" in out

    def test_agent_info(self, addr):
        code, out = run_cli(["agent-info", "-address", addr])
        assert code == 0
        assert "nomad" in out

    def test_operator_raft_list(self, addr):
        code, out = run_cli(["operator-raft-list", "-address", addr])
        assert code == 0
        assert "leader" in out

    def test_version(self):
        code, out = run_cli(["version"])
        assert code == 0
        assert "nomad-tpu v" in out

    def test_init(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        code, out = run_cli(["init"])
        assert code == 0
        assert (tmp_path / "example.nomad").exists()
        # the generated file must itself parse
        from nomad_tpu.jobspec import parse_file
        job = parse_file(str(tmp_path / "example.nomad"))
        assert job.id == "example"
        code, out = run_cli(["init"])
        assert code == 1  # already exists

    def test_dispatch(self, addr, tmp_path):
        from nomad_tpu import mock
        from nomad_tpu.api import NomadAPI
        from nomad_tpu.structs import structs as s
        api = NomadAPI(addr)
        job = mock.job()
        job.parameterized_job = s.ParameterizedJobConfig(payload="optional")
        for t in job.task_groups[0].tasks:
            t.driver = "mock_driver"
            t.config = {"run_for": "5s"}
            t.resources = s.Resources(cpu=20, memory_mb=16)
            t.services = []
        api.jobs.register(job)
        pfile = tmp_path / "payload.txt"
        pfile.write_text("hello")
        code, out = run_cli(["job-dispatch", "-address", addr, "-detach",
                             job.id, str(pfile)])
        assert code == 0
        assert "Dispatched Job ID" in out

    def test_no_command_prints_help(self):
        code, out = run_cli([])
        assert code == 1
        assert "usage" in out.lower()


class TestClusterVerbs:
    def test_keygen_and_keyring(self, tmp_path):
        out = run_cli(["keygen"])
        assert out[0] == 0
        key = out[1].strip()
        import base64
        assert len(base64.b64decode(key)) == 32

        d = str(tmp_path)
        code, o = run_cli(["keyring", "-data-dir", d, "-install", key])
        assert code == 0 and "Installed" in o
        code, o = run_cli(["keyring", "-data-dir", d, "-list"])
        assert code == 0 and key in o and "(primary)" in o
        code, o = run_cli(["keyring", "-data-dir", d, "-remove", key])
        assert code == 1  # primary cannot be removed
        code, o = run_cli(["keygen"])
        key2 = o.strip()
        run_cli(["keyring", "-data-dir", d, "-install", key2])
        code, o = run_cli(["keyring", "-data-dir", d, "-use", key2])
        assert code == 0
        code, o = run_cli(["keyring", "-data-dir", d, "-remove", key])
        assert code == 0

    def test_keyring_via_agent_http(self, addr, agent, tmp_path):
        """Default mode matches the reference: keyring verbs go through
        the agent HTTP API (command/keyring.go:66-97)."""
        prev = agent.config.data_dir
        agent.config.data_dir = str(tmp_path)
        try:
            code, o = run_cli(["keygen"])
            key = o.strip()
            code, o = run_cli(["keyring", "-address", addr,
                               "-install", key])
            assert code == 0 and "Installed" in o
            code, o = run_cli(["keyring", "-address", addr, "-list"])
            assert code == 0 and key in o and "(primary)" in o
            code, o = run_cli(["keyring", "-address", addr,
                               "-remove", key])
            assert code == 1  # primary protected, surfaced as an error
        finally:
            agent.config.data_dir = prev

    def test_server_join_and_force_leave(self, addr):
        from nomad_tpu.server import Server, ServerConfig

        other = Server(ServerConfig(node_name="joiner", enable_rpc=True,
                                    num_schedulers=0))
        other.start()
        try:
            code, o = run_cli(["server-join", "-address", addr,
                               other.config.rpc_advertise])
            assert code == 0 and "Joined 1 servers" in o

            code, o = run_cli(["server-members", "-address", addr])
            assert code == 0 and "joiner" in o

            code, o = run_cli(["server-force-leave", "-address", addr,
                               "joiner"])
            assert code == 0
        finally:
            other.shutdown()


class TestJsonFlags:
    """-json on status/node-status/alloc-status (VERDICT r4 #8): raw API
    JSON of the object, like the reference's -json mode."""

    def test_status_json(self, addr, jobfile):
        import json as json_mod

        code, out = run_cli(["run", "-address", addr, jobfile])
        assert code == 0, out
        code, out = run_cli(["status", "-address", addr, "-json",
                             "cli-demo"])
        assert code == 0, out
        obj = json_mod.loads(out)
        assert obj["ID"] == "cli-demo"
        assert obj["TaskGroups"][0]["Count"] == 2

    def test_node_status_json(self, addr):
        import json as json_mod

        from nomad_tpu.api import NomadAPI
        nodes, _ = NomadAPI(addr).nodes.list()
        code, out = run_cli(["node-status", "-address", addr, "-json",
                             nodes[0]["ID"]])
        assert code == 0, out
        obj = json_mod.loads(out)
        assert obj["ID"] == nodes[0]["ID"]
        assert "Attributes" in obj

    def test_alloc_status_json(self, addr):
        import json as json_mod

        from nomad_tpu.api import NomadAPI
        allocs, _ = NomadAPI(addr).jobs.allocations("cli-demo")
        code, out = run_cli(["alloc-status", "-address", addr, "-json",
                             allocs[0]["ID"]])
        assert code == 0, out
        obj = json_mod.loads(out)
        assert obj["ID"] == allocs[0]["ID"]


class TestOperatorRemovePeerCLI:
    """CLI → SDK → HTTP DELETE /v1/operator/raft/peer chain
    (command/operator_raft_remove.go)."""

    def test_unknown_peer_errors(self, addr):
        code, out = run_cli(["operator-raft-remove-peer", "-address", addr,
                             "-peer-address", "10.9.9.9:4647"])
        assert code == 1
        assert "Error removing peer" in out

    def test_refuses_current_leader(self, addr, agent):
        code, out = run_cli(["operator-raft-remove-peer", "-address", addr,
                             "-peer-address",
                             agent.server.config.rpc_advertise])
        assert code == 1
        assert "Error removing peer" in out


class TestMoreJsonAndDetailedFlags:
    def test_eval_status_json(self, addr, jobfile):
        import json as json_mod

        from nomad_tpu.api import NomadAPI
        run_cli(["run", "-address", addr, jobfile])
        allocs, _ = NomadAPI(addr).jobs.allocations("cli-demo")
        eval_id = allocs[0]["EvalID"]
        code, out = run_cli(["eval-status", "-address", addr, "-json",
                             eval_id])
        assert code == 0, out
        assert json_mod.loads(out)["ID"] == eval_id

    def test_server_members_detailed_and_json(self, addr):
        import json as json_mod

        code, out = run_cli(["server-members", "-address", addr,
                             "-detailed"])
        assert code == 0
        assert "Tags" in out and "region=" in out
        code, out = run_cli(["server-members", "-address", addr, "-json"])
        assert code == 0
        members = json_mod.loads(out)
        assert members and members[0]["Name"]


class TestDebugCommand:
    def test_debug_writes_bundle_file(self, agent, addr, tmp_path):
        import json as json_mod

        # /v1/debug/* is gated; dev config leaves it off.
        agent.config.enable_debug = True
        try:
            dest = str(tmp_path / "bundle.json")
            code, out = run_cli(["debug", "-address", addr,
                                 "-reason", "cli.smoke", "-output", dest])
            assert code == 0, out
            assert dest in out and "cli.smoke" in out
            with open(dest, encoding="utf-8") as fh:
                bundle = json_mod.loads(fh.read())
            assert bundle["Reason"] == "cli.smoke"
            for key in ("Spans", "Events", "Profile", "Locks", "Threads",
                        "Servers"):
                assert key in bundle, key
            assert any(sv["Name"] == agent.server.config.node_name
                       for sv in bundle["Servers"])
        finally:
            agent.config.enable_debug = False

    def test_debug_gated_without_enable_debug(self, agent, addr):
        assert not agent.config.enable_debug
        code, out = run_cli(["debug", "-address", addr])
        assert code == 1
        assert "Error" in out
