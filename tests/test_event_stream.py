"""Cluster event stream tests (server/event_broker.py +
/v1/event/stream + the `nomad-tpu events` consumer path).

Covers the broker mechanics (topic/key filters, bounded ring, index
resume, out-of-ring error, slow-subscriber shedding), the write-path
publishers (monotonic raft-index order across tables, eval/span
correlation with the PR 3 tracing plane), the HTTP/API surface, and —
the acceptance scenario — a chaos node-blackout→lost→reschedule
incident reconstructed from the event stream output alone.
"""
import json
import os
import threading
import time

import pytest

from nomad_tpu import fault, mock
from nomad_tpu.api import APIError, NomadAPI
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.event_broker import (
    EventBroker,
    EventIndexError,
    parse_topic_filter,
)
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node():
    n = mock.node()
    n.resources.networks = []
    n.reserved.networks = []
    return n


def make_job(count=1):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


def drain(sub, timeout=0.2):
    out = []
    while True:
        ev = sub.next(timeout=timeout)
        if ev is None:
            return out
        out.append(ev)


def mk_event(broker, index, topic="Node", etype="NodeUpdated", key="n1"):
    return broker.make_event(topic, etype, key, index)


# ---------------------------------------------------------------------------
# broker mechanics
# ---------------------------------------------------------------------------


class TestBrokerMechanics:
    def test_topic_and_key_filters(self):
        b = EventBroker(ring_size=64)
        every = b.subscribe()
        nodes = b.subscribe(topics=parse_topic_filter("Node"))
        one_key = b.subscribe(topics=parse_topic_filter("Node:n2,Eval"))
        b.publish([mk_event(b, 1, "Node", "NodeUpdated", "n1"),
                   mk_event(b, 2, "Node", "NodeUpdated", "n2"),
                   mk_event(b, 3, "Eval", "EvalUpdated", "e1"),
                   mk_event(b, 4, "Alloc", "AllocPlaced", "a1")])
        assert len(drain(every)) == 4
        assert [e.key for e in drain(nodes)] == ["n1", "n2"]
        assert [(e.topic, e.key) for e in drain(one_key)] == [
            ("Node", "n2"), ("Eval", "e1")]

    def test_parse_topic_filter_shapes(self):
        assert parse_topic_filter("") is None
        assert parse_topic_filter("*") is None
        assert parse_topic_filter("Node") == {"Node": set()}
        assert parse_topic_filter("Node:a,Node:b") == {"Node": {"a", "b"}}
        # A bare topic wins over a keyed entry regardless of order.
        assert parse_topic_filter("Node:a,Node") == {"Node": set()}
        assert parse_topic_filter("Node,Node:a") == {"Node": set()}

    def test_index_resume_replays_buffered(self):
        b = EventBroker(ring_size=64)
        for i in range(1, 11):
            b.publish([mk_event(b, i)])
        sub = b.subscribe(from_index=4)
        got = drain(sub)
        assert [e.index for e in got] == list(range(4, 11))
        # live events continue after the replay, in order
        b.publish([mk_event(b, 11)])
        assert [e.index for e in drain(sub)] == [11]

    def test_out_of_ring_resume_errors_with_oldest(self):
        b = EventBroker(ring_size=8)  # 8 is the broker's floor
        for i in range(1, 13):  # ring holds 5..12, evicted through 4
            b.publish([mk_event(b, i)])
        assert b.oldest_buffered_index() == 5
        with pytest.raises(EventIndexError) as exc:
            b.subscribe(from_index=3)
        assert exc.value.oldest == 5
        assert "oldest buffered index is 5" in str(exc.value)
        # The first still-fully-buffered index works.
        sub = b.subscribe(from_index=5)
        assert [e.index for e in drain(sub)] == list(range(5, 13))

    def test_lagging_subscriber_is_shed(self):
        b = EventBroker(ring_size=1024)
        sub = b.subscribe(max_pending=8)
        for i in range(1, 20):
            b.publish([mk_event(b, i)])
        # Overflowed: closed with a lag error instead of unbounded growth;
        # the broker itself keeps publishing.
        assert sub.closed
        assert "lagging" in (sub.close_error or "")
        assert b.stats()["published"] == 19

    def test_eval_correlation_from_tracing_span(self):
        from nomad_tpu.utils import tracing

        b = EventBroker(ring_size=16)
        tracing.enable()
        try:
            tr = tracing.TRACER
            with tr.span("worker.attempt", eval_id="ev-123"):
                b.publish_one("Alloc", "AllocPlaced", "a1", 5)
        finally:
            tracing.disable()
        ev = b.buffered()[0]
        assert ev.eval_id == "ev-123"
        assert ev.span_id > 0


# ---------------------------------------------------------------------------
# write-path publishers on a live server
# ---------------------------------------------------------------------------


class TestServerEventPublish:
    def test_disarmed_by_default_and_armed_on_subscribe(self):
        srv = Server(ServerConfig(num_schedulers=0))
        srv.start()
        try:
            assert srv.state.event_broker is None
            n = make_node()
            srv.node_register(n)
            assert srv.event_broker.buffered() == []
            sub = srv.event_stream_subscribe()
            assert srv.state.event_broker is srv.event_broker
            srv.node_update_status(n.id, s.NODE_STATUS_DOWN)
            got = drain(sub)
            assert [(e.topic, e.type) for e in got] == [
                ("Node", "NodeStatusUpdated")]
        finally:
            srv.shutdown()

    def test_full_lifecycle_monotonic_and_correlated(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_EVENTS", "1")
        srv = Server(ServerConfig(num_schedulers=1))
        srv.start()
        try:
            n = make_node()
            srv.node_register(n)
            srv.node_update_status(n.id, s.NODE_STATUS_READY)
            job = make_job()
            _, eval_id = srv.job_register(job)
            assert wait_until(lambda: any(
                e.topic == s.TOPIC_EVAL and e.key == eval_id
                and e.payload.get("Status") == s.EVAL_STATUS_COMPLETE
                for e in srv.event_broker.buffered()), timeout=30.0)
            events = srv.event_broker.buffered()
            indexes = [e.index for e in events]
            assert indexes == sorted(indexes)
            pairs = [(e.topic, e.type) for e in events]
            assert ("Node", "NodeRegistered") in pairs
            assert ("Job", "JobRegistered") in pairs
            assert ("Alloc", "AllocPlaced") in pairs
            assert ("Plan", "PlanApplied") in pairs
            assert ("Eval", "EvalAcked") in pairs
            # The placement event carries the eval id that caused it.
            placed = next(e for e in events if e.type == "AllocPlaced")
            assert placed.eval_id == eval_id
            plan = next(e for e in events if e.type == "PlanApplied")
            assert plan.eval_id == eval_id and plan.payload["Placed"] == 1
        finally:
            srv.shutdown()

    def test_snapshot_writes_do_not_publish(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_EVENTS", "1")
        srv = Server(ServerConfig(num_schedulers=0))
        srv.start()
        try:
            snap = srv.state.snapshot()
            assert snap.event_broker is None
            snap.upsert_job(99, make_job())
            assert srv.event_broker.buffered() == []
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# HTTP + api client + CLI surface
# ---------------------------------------------------------------------------


def _server_agent_config():
    from nomad_tpu.agent import AgentConfig

    cfg = AgentConfig()
    cfg.dev_mode = True            # ephemeral RPC port
    cfg.server.enabled = True
    cfg.ports.http = 0
    return cfg


class TestEventStreamHTTP:
    @pytest.fixture()
    def agent(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_EVENTS", "1")
        from nomad_tpu.agent import Agent

        a = Agent(_server_agent_config())
        a.start()
        yield a
        a.shutdown()

    def test_backlog_dump_filters_and_resume(self, agent):
        api = NomadAPI(agent.http.address)
        srv = agent.server
        nodes = [make_node() for _ in range(3)]
        for n in nodes:
            srv.node_register(n)
            srv.node_update_status(n.id, s.NODE_STATUS_READY)
        job = make_job()
        _, eval_id = srv.job_register(job)
        assert wait_until(
            lambda: srv.state.allocs_by_job(None, job.id, True), timeout=30.0)
        assert wait_until(lambda: any(
            e.type == "EvalAcked" for e in srv.event_broker.buffered()),
            timeout=10.0)

        events = list(api.events.stream(follow=False))
        assert events, "no-follow dump returned nothing"
        indexes = [e["Index"] for e in events]
        assert indexes == sorted(indexes)
        types = {e["Type"] for e in events}
        assert {"NodeRegistered", "JobRegistered", "AllocPlaced",
                "PlanApplied"} <= types
        # topic filter: Node events only
        node_events = list(api.events.stream(topics=["Node"], follow=False))
        assert node_events and all(e["Topic"] == "Node"
                                   for e in node_events)
        # index resume over HTTP: no gaps at/after the resume point
        mid = events[len(events) // 2]["Index"]
        resumed = list(api.events.stream(index=mid, follow=False))
        want = [(e["Index"], e["Topic"], e["Type"], e["Key"])
                for e in events if e["Index"] >= mid]
        got = [(e["Index"], e["Topic"], e["Type"], e["Key"])
               for e in resumed]
        assert set(want) <= set(got)

    def test_follow_mode_streams_live_events(self, agent):
        api = NomadAPI(agent.http.address)
        srv = agent.server
        got = []
        done = threading.Event()

        def consume():
            for ev in api.events.stream(topics=["Node"]):
                got.append(ev)
                if len(got) >= 2:
                    break
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the subscription attach
        n = make_node()
        srv.node_register(n)
        srv.node_update_status(n.id, s.NODE_STATUS_DOWN)
        assert done.wait(10.0)
        assert [e["Type"] for e in got] == ["NodeRegistered",
                                           "NodeStatusUpdated"]

    def test_out_of_ring_resume_is_400_with_oldest(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_EVENTS", "1")
        monkeypatch.setenv("NOMAD_TPU_EVENTS_RING", "8")
        from nomad_tpu.agent import Agent

        a = Agent(_server_agent_config())
        a.start()
        try:
            srv = a.server
            for _ in range(6):
                n = make_node()
                srv.node_register(n)
                srv.node_update_status(n.id, s.NODE_STATUS_DOWN)
                srv.node_update_status(n.id, s.NODE_STATUS_READY)
            assert srv.event_broker.stats()["evicted"] > 0
            api = NomadAPI(a.http.address)
            with pytest.raises(APIError) as exc:
                list(api.events.stream(index=1, follow=False))
            assert exc.value.code == 400
            assert "oldest buffered index" in str(exc.value)
        finally:
            a.shutdown()

    def test_cli_events_no_follow(self, agent):
        import io

        from nomad_tpu.cli.commands import main as cli_main

        srv = agent.server
        n = make_node()
        srv.node_register(n)
        out = io.StringIO()
        rc = cli_main(["events", "-no-follow", "-topic", "Node",
                       "-address", agent.http.address], out)
        assert rc == 0
        text = out.getvalue()
        assert "Node/NodeRegistered" in text
        out_json = io.StringIO()
        rc = cli_main(["events", "-no-follow", "-json",
                       "-address", agent.http.address], out_json)
        assert rc == 0
        first = json.loads(out_json.getvalue().splitlines()[0])
        assert {"Topic", "Type", "Key", "Index", "Payload"} <= set(first)


# ---------------------------------------------------------------------------
# the acceptance scenario: chaos incident reconstruction from the stream
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosIncidentReconstruction:
    def test_blackout_lost_reschedule_from_event_stream_alone(self):
        """A node blackout → down → allocs lost → rescheduled incident,
        reconstructed end-to-end from /v1/event/stream output ALONE: the
        heartbeat expiry, the down transition, the lost alloc, the
        node-update eval, and the replacement placement on the surviving
        node — in monotonic raft-index order, with a mid-incident
        disconnect+resume observing no gaps."""
        from nomad_tpu.agent import Agent

        a = Agent(_server_agent_config())
        srv = a.server
        srv.heartbeat.min_ttl = 0.3
        srv.heartbeat.max_per_second = 1000.0
        srv.heartbeat.grace = 0.2
        a.start()
        stop = threading.Event()
        try:
            api = NomadAPI(a.http.address)
            nodes = [make_node() for _ in range(2)]
            for n in nodes:
                srv.node_register(n)
                srv.node_update_status(n.id, s.NODE_STATUS_READY)

            def heartbeater():
                while not stop.is_set():
                    for n in nodes:
                        act = fault.faultpoint(
                            "rpc.send", method="Node.UpdateStatus",
                            node_id=n.id, side="client")
                        if act is not None and act.kind == "drop":
                            continue
                        try:
                            srv.node_update_status(n.id,
                                                   s.NODE_STATUS_READY)
                        except Exception:
                            pass
                    stop.wait(0.1)

            threading.Thread(target=heartbeater, daemon=True).start()

            job = make_job(1)
            srv.job_register(job)
            assert wait_until(lambda: [
                a_ for a_ in srv.state.allocs_by_job(None, job.id, True)
                if not a_.terminal_status()], timeout=30.0)
            victim = [a_ for a_ in srv.state.allocs_by_job(None, job.id,
                                                           True)
                      if not a_.terminal_status()][0].node_id
            other = next(n.id for n in nodes if n.id != victim)

            fault.arm({"seed": 13, "faults": [
                {"point": "rpc.send", "action": "drop",
                 "match": {"node_id": victim}}]})

            def recovered():
                allocs = srv.state.allocs_by_job(None, job.id, True)
                lost = [x for x in allocs
                        if x.client_status == s.ALLOC_CLIENT_STATUS_LOST]
                live = [x for x in allocs if not x.terminal_status()
                        and x.client_status != s.ALLOC_CLIENT_STATUS_LOST]
                return (len(lost) == 1 and len(live) == 1
                        and live[0].node_id == other)

            assert wait_until(recovered, timeout=30.0)
            fault.disarm()
            stop.set()

            # ---- reconstruction, from the HTTP stream alone ----
            events = list(api.events.stream(follow=False))
            indexes = [e["Index"] for e in events]
            assert indexes == sorted(indexes), \
                "events must arrive in monotonic raft-index order"

            def first(pred):
                return next(i for i, e in enumerate(events) if pred(e))

            expired_i = first(
                lambda e: e["Type"] == "NodeHeartbeatExpired"
                and e["Key"] == victim)
            down_i = first(
                lambda e: e["Type"] == "NodeStatusUpdated"
                and e["Key"] == victim
                and e["Payload"].get("Status") == s.NODE_STATUS_DOWN
                and e["Payload"].get("Previous") == s.NODE_STATUS_READY)
            lost_i = first(
                lambda e: e["Type"] == "AllocLost"
                and e["Payload"].get("NodeID") == victim
                and e["Payload"].get("JobID") == job.id)
            placed_i = first(
                lambda e: e["Type"] == "AllocPlaced"
                and e["Payload"].get("NodeID") == other
                and e["Payload"].get("JobID") == job.id)
            # The lost/placed writes correlate (via EvalID) to node-update
            # evals for the blacked-out node, and those evals' creation
            # events sit between the down transition and the plan writes.
            node_eval_ids = {
                e["Key"] for e in events
                if e["Type"] == "EvalUpdated"
                and e["Payload"].get("TriggeredBy")
                == s.EVAL_TRIGGER_NODE_UPDATE
                and e["Payload"].get("NodeID") == victim}
            assert events[lost_i]["EvalID"] in node_eval_ids
            assert events[placed_i]["EvalID"] in node_eval_ids
            eval_i = first(
                lambda e: e["Type"] == "EvalUpdated"
                and e["Key"] == events[lost_i]["EvalID"])
            assert expired_i < down_i < eval_i
            assert eval_i < lost_i
            assert down_i < placed_i

            # ---- disconnect + resume: no gaps while buffered ----
            mid = events[down_i]["Index"]
            resumed = list(api.events.stream(index=mid, follow=False))
            want = [(e["Index"], e["Topic"], e["Type"], e["Key"])
                    for e in events if e["Index"] >= mid]
            got = [(e["Index"], e["Topic"], e["Type"], e["Key"])
                   for e in resumed]
            assert set(want) <= set(got), "resume observed a gap"
        finally:
            stop.set()
            fault.disarm()
            a.shutdown()
