"""Follower-read scheduling (ISSUE 10): eval workers on follower
servers schedule off their locally replicated FSM and forward plans to
the leader's serialized plan-apply (nomad_tpu/server/follower_sched.py).

Invariant discipline mirrors test_multiworker: node CHOICE is
randomized, so correctness is outcome-level — every job fully placed
exactly once (no lost evals, no double placements), zero overcommit,
every eval terminal — now with the scheduling spread across SERVERS
instead of threads, and with leader failover in the middle.
"""
import time

import pytest

from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.eval_broker import EvalBrokerError
from nomad_tpu.server.follower_sched import (FollowerLagError,
                                             FollowerWorker,
                                             LeaderChannel, RemoteBroker)
from nomad_tpu.server.rpc import NoLeaderError
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node(i, cpu=4000, mem=8192):
    return s.Node(
        id=f"fs-node-{i:04d}", datacenter="dc1", name=f"fs-node-{i:04d}",
        attributes={"kernel.name": "linux", "driver.exec": "1"},
        resources=s.Resources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024,
                              iops=1000),
        reserved=s.Resources(), status=s.NODE_STATUS_READY)


def make_job(n, count=2, cpu=100, mem=128, priority=50):
    jid = f"fs-job-{n:05d}"
    return s.Job(
        region="global", id=jid, name=jid, type=s.JOB_TYPE_SERVICE,
        priority=priority, datacenters=["dc1"],
        task_groups=[s.TaskGroup(
            name="tg", count=count,
            ephemeral_disk=s.EphemeralDisk(size_mb=10),
            tasks=[s.Task(name="t", driver="exec",
                          config={"command": "/bin/date"},
                          resources=s.Resources(cpu=cpu, memory_mb=mem),
                          log_config=s.LogConfig())])])


def make_cluster(n=3, follower_schedulers=2, num_schedulers=0):
    """n in-process servers over real RPC.  num_schedulers=0 keeps every
    server free of leader-local workers, so completions can ONLY come
    through the follower-read path."""
    servers = []
    first = None
    for i in range(n):
        cfg = ServerConfig(
            node_name=f"fs-{i + 1}", enable_rpc=True, bootstrap_expect=n,
            start_join=[first] if first else [],
            num_schedulers=num_schedulers,
            follower_schedulers=follower_schedulers,
            min_heartbeat_ttl=60.0)
        srv = Server(cfg)
        if first is None:
            first = srv.config.rpc_advertise
        servers.append(srv)
    for srv in servers:
        srv.start()
    return servers


def find_leader(servers):
    for srv in servers:
        if srv.is_leader() and srv.raft.is_raft_leader():
            return srv
    return None


def assert_drain_invariants(leader, eval_ids, n_jobs, count):
    evals = [leader.state.eval_by_id(None, eid) for eid in eval_ids]
    assert all(ev is not None and ev.status == s.EVAL_STATUS_COMPLETE
               for ev in evals), [getattr(ev, "status", None)
                                  for ev in evals]
    allocs = [a for a in leader.state.allocs(None)
              if not a.terminal_status()]
    by_job = {}
    for a in allocs:
        by_job.setdefault(a.job_id, []).append(a)
    assert len(by_job) == n_jobs
    for job_id, job_allocs in by_job.items():
        assert len(job_allocs) == count, \
            f"{job_id}: {len(job_allocs)} allocs (want {count})"
        assert len({a.id for a in job_allocs}) == count
        assert len({a.name for a in job_allocs}) == count
    node_map = {n.id: n for n in leader.state.nodes(None)}
    usage = {}
    for a in allocs:
        cpu, mem = usage.get(a.node_id, (0, 0))
        usage[a.node_id] = (cpu + a.resources.cpu,
                            mem + a.resources.memory_mb)
    for node_id, (cpu, mem) in usage.items():
        node = node_map[node_id]
        assert cpu <= node.resources.cpu - node.reserved.cpu
        assert mem <= node.resources.memory_mb - node.reserved.memory_mb


class TestFollowerScheduling:
    N_JOBS = 30
    COUNT = 2

    def test_followers_drain_with_invariants(self):
        """A 3-voter cluster with NO leader-local workers drains a
        30-job backlog entirely through follower-read scheduling: plans
        forwarded over RPC, applied by the leader, replicated to every
        FSM — with the full multi-worker invariant set intact."""
        servers = make_cluster(3)
        try:
            assert wait_until(lambda: find_leader(servers) is not None,
                              15.0)
            leader = find_leader(servers)
            followers = [x for x in servers if x is not leader]
            assert wait_until(lambda: all(
                len(x.raft.peers) == 3 for x in servers))
            for i in range(30):
                leader.node_register(make_node(i))
            eval_ids = []
            for n in range(self.N_JOBS):
                _, eid = leader.job_register(make_job(n, count=self.COUNT))
                eval_ids.append(eid)
            assert wait_until(
                lambda: all(
                    (ev := leader.state.eval_by_id(None, eid)) is not None
                    and ev.terminal_status() for eid in eval_ids),
                timeout=90.0), "evals did not all reach a terminal state"
            assert_drain_invariants(leader, eval_ids, self.N_JOBS,
                                    self.COUNT)
            # The work actually crossed the wire: plans were forwarded
            # by follower servers, none of them errored.
            forwarded = sum(f.leader_channel.stats()["ForwardedPlans"]
                            for f in followers)
            assert forwarded >= self.N_JOBS
            # Every server's FSM converges on the same placements.
            want = self.N_JOBS * self.COUNT
            assert wait_until(lambda: all(
                len([a for a in x.state.allocs(None)
                     if not a.terminal_status()]) == want
                for x in servers), 15.0)
            # The leader's own follower workers stayed parked.
            assert leader.leader_channel.stats()["ForwardedPlans"] == 0
        finally:
            for srv in servers:
                srv.shutdown()

    @pytest.mark.chaos
    @pytest.mark.parametrize("seed", [7, 23])
    def test_leader_failover_with_inflight_plans(self, seed):
        """Kill the leader while follower workers are mid-drain (plans
        in flight): the survivors re-elect, the new leader's restore
        pass re-enqueues pending evals, the post-failover fence floor
        makes followers replicate past every pre-failover plan before
        scheduling — and the final state shows NO double placement and
        NO lost eval."""
        servers = make_cluster(3)
        try:
            assert wait_until(lambda: find_leader(servers) is not None,
                              15.0)
            leader = find_leader(servers)
            survivors = [x for x in servers if x is not leader]
            assert wait_until(lambda: all(
                len(x.raft.peers) == 3 for x in servers))
            for i in range(30):
                leader.node_register(make_node(i))
            eval_ids = []
            for n in range(self.N_JOBS):
                _, eid = leader.job_register(
                    make_job(n, count=self.COUNT))
                eval_ids.append(eid)
            # Let the drain get going, then kill the leader mid-flight
            # (seeded delay varies WHERE in the drain the failover
            # lands).
            assert wait_until(lambda: any(
                (ev := leader.state.eval_by_id(None, eid)) is not None
                and ev.terminal_status() for eid in eval_ids), 60.0)
            time.sleep(0.05 * (seed % 5))
            leader.shutdown()

            assert wait_until(lambda: find_leader(survivors) is not None,
                              30.0), "survivors did not re-elect"
            new_leader = find_leader(survivors)
            assert wait_until(
                lambda: all(
                    (ev := new_leader.state.eval_by_id(None, eid))
                    is not None and ev.terminal_status()
                    for eid in eval_ids),
                timeout=120.0), "drain did not finish after failover"
            # No lost eval, no double placement, no overcommit.
            assert_drain_invariants(new_leader, eval_ids, self.N_JOBS,
                                    self.COUNT)
        finally:
            for srv in servers:
                srv.shutdown()


class _StubChannel:
    def __init__(self):
        self.calls = []

    def call(self, method, body, timeout=10.0):
        self.calls.append((method, body))
        return {}


class _StubRaft:
    """Raft whose applied index is pinned — a follower that can never
    catch up."""

    def __init__(self, applied=5):
        self._applied = applied

    def applied_index(self):
        return self._applied

    def applied_index_relaxed(self):
        return self._applied


class TestLagFence:
    def test_lagging_follower_hands_back_instead_of_scheduling(self):
        """An eval whose plan fence exceeds the follower's replicated
        log must NOT be scheduled from a stale local snapshot — the
        worker raises (→ nack → redelivery) after the bounded wait."""
        channel = _StubChannel()
        w = FollowerWorker(_StubRaft(applied=5), channel,
                           is_leader_fn=lambda: False)
        # Simulate a dequeue that carried fence 100 for the job.
        w.plan_queue.note_applied("job-x", 100)
        ev = s.Evaluation(id="e1", job_id="job-x",
                          type=s.JOB_TYPE_SERVICE,
                          status=s.EVAL_STATUS_PENDING,
                          job_modify_index=3)
        # Shrink the catch-up window so the test is fast; the wait is
        # real (backed-off polling against the pinned index).
        import nomad_tpu.server.follower_sched as fs_mod
        saved = fs_mod.RAFT_SYNC_LIMIT
        fs_mod.RAFT_SYNC_LIMIT = 0.1
        try:
            with pytest.raises(FollowerLagError):
                w.invoke_scheduler(ev, "tok")
        finally:
            fs_mod.RAFT_SYNC_LIMIT = saved
        # Nothing was scheduled: no plan submit, no eval update.
        assert not any(m == "Plan.Submit" for m, _ in channel.calls)

    def test_trigger_index_alone_also_fences(self):
        channel = _StubChannel()
        w = FollowerWorker(_StubRaft(applied=5), channel,
                           is_leader_fn=lambda: False)
        ev = s.Evaluation(id="e2", job_id="job-y",
                          type=s.JOB_TYPE_SERVICE,
                          status=s.EVAL_STATUS_PENDING,
                          job_modify_index=50)  # beyond applied=5
        import nomad_tpu.server.follower_sched as fs_mod
        saved = fs_mod.RAFT_SYNC_LIMIT
        fs_mod.RAFT_SYNC_LIMIT = 0.1
        try:
            with pytest.raises(FollowerLagError):
                w.invoke_scheduler(ev, "tok")
        finally:
            fs_mod.RAFT_SYNC_LIMIT = saved


class _HintPool:
    """Fake ConnPool: the first address answers NoLeaderError with a
    leader hint, the hinted address answers."""

    def __init__(self, leader_addr):
        self.leader_addr = leader_addr
        self.calls = []

    def call(self, addr, method, body, channel=None, timeout=None):
        self.calls.append(addr)
        if addr != self.leader_addr:
            raise NoLeaderError(self.leader_addr)
        return {"ok": True}


class TestLeaderChannel:
    def test_no_leader_hint_is_followed(self):
        pool = _HintPool("127.0.0.1:4647")
        ch = LeaderChannel(pool, lambda: "127.0.0.1:9999",
                           my_addr="127.0.0.1:1111")
        assert ch.call("Status.Ping", {}) == {"ok": True}
        assert pool.calls == ["127.0.0.1:9999", "127.0.0.1:4647"]

    def test_no_known_leader_raises(self):
        ch = LeaderChannel(_HintPool("x"), lambda: "",
                           my_addr="127.0.0.1:1111")
        with pytest.raises(NoLeaderError):
            ch.call("Status.Ping", {})

    def test_own_address_raises(self):
        """When WE are the leader the channel refuses (the local worker
        pool owns the broker; looping RPCs to ourselves would race
        it)."""
        ch = LeaderChannel(_HintPool("x"), lambda: "127.0.0.1:1111",
                           my_addr="127.0.0.1:1111")
        with pytest.raises(NoLeaderError):
            ch.call("Status.Ping", {})

    def test_remote_broker_errors_surface_as_broker_errors(self):
        class _Boom:
            def call(self, *a, **k):
                raise NoLeaderError("")

        ch = LeaderChannel(_Boom(), lambda: "127.0.0.1:2",
                           my_addr="127.0.0.1:1")
        rb = RemoteBroker(ch, {})
        with pytest.raises(EvalBrokerError):
            rb.dequeue_batch([s.JOB_TYPE_SERVICE], 4, 0.0)
