"""Executor subprocess isolation (VERDICT r2 item 5): tasks run under a
detached supervisor (client/driver/supervisor.py ≙ the reference's
go-plugin executor subprocess, client/driver/executor_plugin.go) so the
agent can restart and re-collect exit status and stats."""
import os
import signal
import sys
import time

import pytest

from nomad_tpu.client.driver.executor import (
    ExecCommand,
    SupervisedExecutor,
    attach_supervised,
)

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def _wait_until(fn, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def _mk_cmd(tmp_path, script, name="t"):
    return ExecCommand(
        cmd=sys.executable, args=["-c", script],
        env={"PATH": os.environ.get("PATH", "")},
        cwd=str(tmp_path), task_name=name,
        log_dir=str(tmp_path / "logs"),
    )


class TestSupervisedExecutor:
    def test_exit_code_collected(self, tmp_path):
        ex = SupervisedExecutor(
            _mk_cmd(tmp_path, "import sys; sys.exit(7)"),
            str(tmp_path / "ctl"))
        pid = ex.launch()
        assert pid > 0
        assert ex.exited.wait(15.0)
        assert ex.result.exit_code == 7

    def test_logs_flow_through_supervisor(self, tmp_path):
        ex = SupervisedExecutor(
            _mk_cmd(tmp_path, "print('hello-from-task')"),
            str(tmp_path / "ctl"))
        ex.launch()
        assert ex.exited.wait(15.0)
        logdir = tmp_path / "logs"
        out = b"".join(
            p.read_bytes() for p in logdir.iterdir()
            if "stdout" in p.name)
        assert b"hello-from-task" in out

    def test_signal_and_stats_via_socket(self, tmp_path):
        # The task signals handler-readiness through a marker file:
        # interpreter startup is slow in this environment (site hook
        # pre-imports jax), so signaling on rss>0 alone races the
        # signal.signal() call and the default disposition kills the task.
        ready = tmp_path / "ready"
        script = (
            "import pathlib, signal, sys, time\n"
            "signal.signal(signal.SIGUSR1, lambda *_: sys.exit(42))\n"
            f"pathlib.Path({str(ready)!r}).write_text('x')\n"
            "time.sleep(60)\n")
        ex = SupervisedExecutor(_mk_cmd(tmp_path, script),
                                str(tmp_path / "ctl"))
        ex.launch()
        assert _wait_until(lambda: ex.stats().get("rss_bytes", 0) > 0)
        assert _wait_until(ready.exists)
        ex.send_signal(signal.SIGUSR1)
        assert ex.exited.wait(15.0)
        assert ex.result.exit_code == 42

    def test_shutdown_grace(self, tmp_path):
        ex = SupervisedExecutor(
            _mk_cmd(tmp_path, "import time; time.sleep(120)"),
            str(tmp_path / "ctl"))
        ex.launch()
        t0 = time.monotonic()
        ex.shutdown(grace=3.0)
        assert ex.exited.wait(10.0)
        assert time.monotonic() - t0 < 8.0

    def test_task_survives_agent_death_and_exit_code_captured(self, tmp_path):
        """The VERDICT r2 item-5 scenario: the 'agent' (this process's
        executor object) goes away, the task keeps running under the
        supervisor, finishes with a specific exit code, and a restarted
        agent re-attaches and collects that exact code."""
        marker = tmp_path / "ran"
        script = (
            "import pathlib, time\n"
            f"pathlib.Path({str(marker)!r}).write_text('x')\n"
            "time.sleep(2.0)\n"
            "raise SystemExit(9)\n")
        ctl = str(tmp_path / "ctl")
        ex = SupervisedExecutor(_mk_cmd(tmp_path, script), ctl)
        task_pid = ex.launch()
        assert _wait_until(marker.exists)
        # Simulate agent death: forget the executor entirely (its watcher
        # thread belongs to the dead agent; nothing signals the task).
        del ex

        # Task must still be running under the supervisor.
        os.kill(task_pid, 0)

        # "Restarted agent": re-attach by control dir and collect.
        ex2 = attach_supervised(ctl)
        assert ex2 is not None
        assert ex2.exited.wait(20.0)
        assert ex2.result.exit_code == 9

    def test_reattach_after_task_finished_while_agent_down(self, tmp_path):
        """Exit status persists on disk (exit.json), so the code is
        collectable even when the task ended before the agent returned."""
        ctl = str(tmp_path / "ctl")
        ex = SupervisedExecutor(
            _mk_cmd(tmp_path, "raise SystemExit(5)"), ctl)
        ex.launch()
        assert ex.exited.wait(15.0)
        del ex

        ex2 = attach_supervised(ctl)
        assert ex2 is not None
        assert ex2.exited.wait(15.0)
        assert ex2.result.exit_code == 5

    def test_driver_handle_roundtrip(self, tmp_path):
        """Driver-level open(): the sup:<ctl_dir> handle id re-attaches
        through the registry path the task runner uses on restore."""
        from nomad_tpu.client.driver.exec_drivers import ExecutorHandle

        ctl = str(tmp_path / "ctl")
        ex = SupervisedExecutor(
            _mk_cmd(tmp_path, "import time; time.sleep(30)"), ctl)
        ex.launch()
        handle = ExecutorHandle(ex, "t", 5.0)
        hid = handle.id()
        assert hid == f"sup:{ctl}"

        ex2 = attach_supervised(hid.split(":", 1)[1])
        assert ex2 is not None
        ex2.shutdown(grace=2.0)
        assert ex2.exited.wait(10.0)
