"""Cluster-scale chaos scenarios (ISSUE 12): deterministic network
partitions, crash-restart recovery, and the continuous safety auditor.

Fast fixed-seed scenarios run in tier-1 under the ``chaos`` marker
(including the subprocess kill+restart smoke soak); the full 3-server
soak is additionally marked ``slow`` — its recorded evidence lives in
LOADGEN_r05.json.
"""
import os
import time

import pytest

from nomad_tpu import fault, mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import FSM, MessageType
from nomad_tpu.server.raft import FileLog, MultiRaft
from nomad_tpu.server.rpc import ConnPool, DialError
from nomad_tpu.structs import structs as s

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _always_disarm():
    """No scenario — rule plane OR net plane — may leak across tests."""
    yield
    fault.disarm()
    fault.net_disarm()


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node():
    n = mock.node()
    n.resources.networks = []
    n.reserved.networks = []
    return n


def make_job(count=2):
    j = mock.job()
    j.task_groups[0].count = count
    for t in j.task_groups[0].tasks:
        t.resources.networks = []
    return j


# ---------------------------------------------------------------------------
# the net plane itself
# ---------------------------------------------------------------------------


class TestNetPlane:
    def test_partition_blocks_both_directions_and_heals(self):
        plane = fault.net_partition("p", [["a:1"], ["b:2", "c:3"]])
        assert plane.blocked("a:1", "b:2")
        assert plane.blocked("c:3", "a:1")
        assert not plane.blocked("b:2", "c:3")   # same group
        assert not plane.blocked("a:1", "d:4")   # unlisted → unaffected
        fault.net_heal("p")
        assert not plane.blocked("a:1", "b:2")
        trace = plane.trace()
        assert ("net.partition", "p", "split") in trace
        assert ("net.partition", "p", "heal") in trace

    def test_wildcard_group_isolates_most_specific(self):
        """A ["*"] catch-all group composes with a literal group: the
        listed address is cut off from EVERYONE (the subprocess-isolate
        shape), including unidentified client pools."""
        plane = fault.net_partition("iso", [["*"], ["b:2"]])
        assert plane.blocked("", "b:2")
        assert plane.blocked("b:2", "a:1")
        assert not plane.blocked("a:1", "c:3")
        fault.net_heal()

    def test_asymmetric_rule_seeded_determinism(self):
        """A src→dst drop rule fires one direction only, and the same
        seed yields the same decision sequence — the reproducibility
        contract carried over from the rule plane."""
        def run(seed):
            plane = fault.net_arm({"seed": seed, "rules": [
                {"src": "a:1", "dst": "b:2", "action": "drop",
                 "prob": 0.5}]})
            fires = []
            for _ in range(64):
                fires.append(plane.check("send", "a:1", "b:2") is not None)
                # reverse direction never fires
                assert plane.check("send", "b:2", "a:1") is None
            fault.net_disarm()
            return fires

        a, b, c = run(5), run(5), run(6)
        assert a == b
        assert 0 < sum(a) < 64
        assert a != c

    def test_flap_windows_deterministic_and_scheduled(self):
        w = fault.flap_windows(9, count=3, period=1.0, duty=0.5)
        assert w == fault.flap_windows(9, count=3, period=1.0, duty=0.5)
        assert w != fault.flap_windows(10, count=3, period=1.0, duty=0.5)
        assert all(b > a for a, b in w)
        # A flapping partition honors its windows against the plane's
        # arm anchor: shift the anchor to step through the schedule.
        plane = fault.net_arm()
        plane.partition("flap", [["a:1"], ["b:2"]], windows=[(10.0, 11.0)])
        assert not plane.blocked("a:1", "b:2")    # before the window
        plane._anchor -= 10.5                      # inside the window
        assert plane.blocked("a:1", "b:2")
        plane._anchor -= 5.0                       # past it → healed
        assert not plane.blocked("a:1", "b:2")

    def test_reorder_is_bounded_delay(self):
        plane = fault.net_arm({"seed": 1, "rules": [
            {"action": "reorder", "max_delay": 0.5}]})
        act = plane.check("send", "x", "y")
        assert act is not None
        action, delay = act
        assert action == "reorder" and 0.0 <= delay <= 0.5


class TestDialBackoff:
    def test_dead_peer_dials_gate_instead_of_hammering(self):
        """First dial to a dead address fails for real; an immediate
        second attempt fails FAST from the local backoff gate without
        touching a socket (the redial-storm fix)."""
        pool = ConnPool(timeout=0.5)
        dead = "127.0.0.1:1"
        with pytest.raises(DialError) as e1:
            pool.call(dead, "Status.Ping", {})
        assert "backoff" not in str(e1.value)
        gate = pool._dial_gate[dead]
        assert gate[1] > time.monotonic() - 0.001
        with pytest.raises(DialError) as e2:
            pool.call(dead, "Status.Ping", {})
        assert "dial backoff" in str(e2.value)
        # The gate expires (capped, jittered) and real dials resume.
        time.sleep(max(0.0, gate[1] - time.monotonic()) + 0.01)
        with pytest.raises(DialError) as e3:
            pool.call(dead, "Status.Ping", {})
        assert "dial backoff" not in str(e3.value)
        pool.close()

    def test_gate_clears_on_success(self):
        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=0))
        srv.start()
        pool = ConnPool(timeout=2.0)
        try:
            addr = srv.config.rpc_advertise
            # Seed a (expired) gate entry, then a successful dial must
            # clear it entirely.
            from nomad_tpu.utils.backoff import Backoff
            pool._dial_gate[addr] = [Backoff(), 0.0]
            assert pool.call(addr, "Status.Ping", {}) == {"ok": True}
            assert addr not in pool._dial_gate
        finally:
            pool.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# msgpack residue counter (ROADMAP item 1 residual, ISSUE 12 satellite)
# ---------------------------------------------------------------------------


class TestMsgpackMethodCounter:
    def test_hot_methods_never_ride_msgpack_between_codec_peers(self):
        from nomad_tpu import codec
        from nomad_tpu.api.codec import to_wire

        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=0))
        srv.start()
        pool = ConnPool()
        try:
            before = codec.msgpack_methods()
            addr = srv.config.rpc_advertise
            node = make_node()
            pool.call(addr, "Node.Register", {"Node": to_wire(node)})
            pool.call(addr, "Job.Register",
                      {"Job": to_wire(make_job(1))})
            pool.call(addr, "Status.Ping", {})
            delta = {m: n - before.get(m, 0)
                     for m, n in codec.msgpack_methods().items()
                     if n - before.get(m, 0) > 0}
            hot = {m: n for m, n in delta.items()
                   if m.startswith(codec.HOT_METHOD_PREFIXES)}
            assert hot == {}, (
                f"hot methods rode the msgpack fallback: {hot}")
        finally:
            pool.close()
            srv.shutdown()

    def test_legacy_peer_frames_are_counted_per_method(self):
        from nomad_tpu import codec

        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=0))
        srv.start()
        pool = ConnPool()
        try:
            addr = srv.config.rpc_advertise
            # Pin the address legacy: every frame is reflection msgpack
            # and must show up in the per-method residue profile.
            pool._legacy_addrs.add(addr)
            before = codec.msgpack_methods().get("Status.Ping", 0)
            pool.call(addr, "Status.Ping", {})
            pool.call(addr, "Status.Ping", {})
            assert codec.msgpack_methods().get(
                "Status.Ping", 0) - before == 2
        finally:
            pool.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# cluster harness (in-process, test_cluster-style)
# ---------------------------------------------------------------------------


def make_cluster(tmp_path, n=3, num_schedulers=0, env=None,
                 monkeypatch=None):
    if env:
        for k, v in env.items():
            monkeypatch.setenv(k, v)
    servers = []
    first = None
    for i in range(n):
        cfg = ServerConfig(
            node_name=f"chaos-{i + 1}",
            data_dir=str(tmp_path / f"s{i + 1}"),
            enable_rpc=True, bootstrap_expect=n,
            start_join=[first] if first else [],
            num_schedulers=num_schedulers,
            min_heartbeat_ttl=60.0)
        srv = Server(cfg)
        if first is None:
            first = srv.config.rpc_advertise
        servers.append(srv)
    for srv in servers:
        srv.start()
    return servers


def wait_for_leader(servers, timeout=30.0):
    assert wait_until(lambda: any(
        srv.is_leader() and srv.raft.is_raft_leader()
        for srv in servers), timeout), "no leader elected"
    return next(srv for srv in servers
                if srv.is_leader() and srv.raft.is_raft_leader())


class TestPartitionHealInstallSnapshot:
    def test_partitioned_follower_catches_up_via_chunked_install(
            self, tmp_path, monkeypatch):
        """Split a follower from the leader (both directions), commit
        writes and compact the leader's log past the follower's
        horizon, heal — the follower must catch up via CHUNKED
        InstallSnapshot, converging to an identical FSM fingerprint."""
        servers = make_cluster(
            tmp_path, 3, monkeypatch=monkeypatch,
            env={
                # A partitioned VOTER must not campaign inside the
                # short split (term inflation would measure election
                # churn, not catch-up).
                "NOMAD_TPU_RAFT_ELECTION_MIN_S": "8.0",
                "NOMAD_TPU_RAFT_ELECTION_MAX_S": "12.0",
                "NOMAD_TPU_SNAPSHOT_CHUNK": "512",
            })
        try:
            leader = wait_for_leader(servers)
            victim = next(srv for srv in servers if srv is not leader)
            assert wait_until(lambda: all(
                len(srv.raft.peers) == 3 for srv in servers))

            job0 = make_job(1)
            leader.job_register(job0)
            assert wait_until(lambda: victim.state.job_by_id(
                None, job0.id) is not None)

            fault.net_partition(
                "split", [[leader.config.rpc_advertise],
                          [victim.config.rpc_advertise]])
            jobs = [make_job(1) for _ in range(5)]
            for job in jobs:
                leader.job_register(job)
            # The split is real: the follower sees none of it.
            time.sleep(0.3)
            assert all(victim.state.job_by_id(None, j.id) is None
                       for j in jobs)
            # Compact the leader past the follower's log position so
            # heal-time catch-up MUST take the snapshot path.
            leader.raft.snapshot()
            assert isinstance(leader.raft, MultiRaft)
            assert leader.raft.base_index > 0
            chunks_before = int((leader.metrics.sink.latest()
                                 .get("CounterTotals") or {})
                                .get("nomad.raft.snapshot.chunks_sent", 0))

            fault.net_heal("split")
            assert wait_until(lambda: all(
                victim.state.job_by_id(None, j.id) is not None
                for j in jobs), 30.0), "healed follower did not catch up"
            assert wait_until(
                lambda: victim.raft.base_index >= leader.raft.base_index,
                10.0)
            chunks = int((leader.metrics.sink.latest()
                          .get("CounterTotals") or {})
                         .get("nomad.raft.snapshot.chunks_sent", 0))
            assert chunks - chunks_before >= 2, \
                "catch-up was not a chunked InstallSnapshot"
            # Split it AGAIN (determinism of repeated split/heal) and
            # verify the converged fingerprints agree.
            fault.net_partition(
                "split2", [[leader.config.rpc_advertise],
                           [victim.config.rpc_advertise]])
            job_z = make_job(1)
            leader.job_register(job_z)
            time.sleep(0.2)
            assert victim.state.job_by_id(None, job_z.id) is None
            fault.net_heal("split2")
            assert wait_until(lambda: victim.state.job_by_id(
                None, job_z.id) is not None, 20.0)

            def converged():
                li, lfp = leader.fsm_fingerprint()
                vi, vfp = victim.fsm_fingerprint()
                return li == vi and lfp == vfp

            assert wait_until(converged, 10.0), \
                "FSM fingerprints did not converge after heal"
        finally:
            for srv in servers:
                srv.shutdown()


class TestLeaderKillInFlight:
    def test_leader_death_with_inflight_plans_no_double_placement(
            self, tmp_path):
        """Kill the leader while pipelined plans are in flight through
        its applier: after the survivors elect, every pending eval is
        restored and completes, and NO job ends with more live allocs
        than its count or a duplicate name — the PR 10 fences (token
        fence, post-failover floor) across a real failover."""
        servers = make_cluster(tmp_path, 3, num_schedulers=1)
        try:
            leader = wait_for_leader(servers)
            for srv in servers:
                srv.eval_broker.initial_nack_delay = 0.1
            for _ in range(4):
                leader.node_register(make_node())

            # Widen the in-flight window: every plan commit pays a
            # delay inside the leader's raft apply.
            fault.arm({"seed": 3, "faults": [
                {"point": "raft.apply", "action": "delay", "delay": 0.25,
                 "match": {"msg_type": "APPLY_PLAN_RESULTS"}}]})
            jobs = [make_job(2) for _ in range(4)]
            for job in jobs:
                leader.job_register(job)
            time.sleep(0.3)  # plans now mid-pipeline
            leader.shutdown()
            fault.disarm()

            survivors = [srv for srv in servers if srv is not leader]
            new_leader = wait_for_leader(survivors, timeout=45.0)

            def settled():
                for job in jobs:
                    live = [a for a in new_leader.state.allocs_by_job(
                                None, job.id, True)
                            if not a.terminal_status()]
                    if len(live) != 2:
                        return False
                return True

            assert wait_until(settled, 90.0), \
                "jobs did not settle at their exact count after failover"
            # The invariant, explicitly: never MORE than count, never a
            # duplicate name, on every survivor.
            for srv in survivors:
                for job in jobs:
                    live = [a for a in srv.state.allocs_by_job(
                                None, job.id, True)
                            if not a.terminal_status()]
                    assert len(live) <= 2
                    assert len({a.name for a in live}) == len(live)
        finally:
            for srv in servers:
                srv.shutdown()


# ---------------------------------------------------------------------------
# torn walseg recovery (FileLog)
# ---------------------------------------------------------------------------


class TestTornWalsegRecovery:
    def _apply_nodes(self, log, count):
        nodes = [make_node() for _ in range(count)]
        for n in nodes:
            log.apply(MessageType.NODE_REGISTER, {"node": n})
        return nodes

    def test_torn_sealed_segment_recovers_durable_prefix_exactly(
            self, tmp_path, monkeypatch):
        """A crash between the WAL roll and the snapshot blob leaves
        sealed walseg files as the only copy of their entries; a torn
        tail in one (partial disk write) must recover the longest
        decodable prefix EXACTLY — earlier entries intact, the torn
        record dropped, and later appends durable at the right index."""
        d = str(tmp_path / "wal")
        fsm = FSM()
        log = FileLog(fsm, d, snapshot_entries=0, snapshot_bytes=0)
        nodes = self._apply_nodes(log, 4)
        # Crash mid-snapshot: the roll seals the WAL into walseg files,
        # then the blob persist dies — segments stay behind.
        def boom(snap_store, index):
            raise OSError("injected crash before snapshot blob")
        monkeypatch.setattr(log, "_persist_snapshot_blob", boom)
        with pytest.raises(OSError):
            log.snapshot()
        log.close()
        segs = [os.path.join(d, f) for f in os.listdir(d)
                if f.startswith("walseg-")]
        assert segs, "crash-before-blob left no sealed segments"
        # Tear the tail of the (single) sealed segment: the last
        # record's bytes are partially lost.
        seg = segs[0]
        size = os.path.getsize(seg)
        with open(seg, "r+b") as fh:
            fh.truncate(size - 7)

        log2 = FileLog(FSM(), d, snapshot_entries=0, snapshot_bytes=0)
        try:
            # Exactly the durable prefix: 1-3 recovered, entry 4 (torn)
            # gone, nothing invented.
            assert log2.applied_index() == 3
            for n in nodes[:3]:
                assert log2.fsm.state.node_by_id(None, n.id) is not None
            assert log2.fsm.state.node_by_id(None, nodes[3].id) is None
            # The index is reusable and appends stay durable.
            extra = make_node()
            _, idx = log2.apply(MessageType.NODE_REGISTER, {"node": extra})
            assert idx == 4
        finally:
            log2.close()

        log3 = FileLog(FSM(), d, snapshot_entries=0, snapshot_bytes=0)
        try:
            assert log3.applied_index() == 4
            assert log3.fsm.state.node_by_id(None, extra.id) is not None
        finally:
            log3.close()


# ---------------------------------------------------------------------------
# the chaos_soak smoke tier: a REAL subprocess kill+restart under load
# ---------------------------------------------------------------------------


class TestChaosSoakSmoke:
    def _assert_clean(self, rep, expect_events):
        aud = rep.get("auditor") or {}
        assert aud.get("violation_count") == 0, aud.get("violations")
        assert (aud.get("checks") or {}).get("fingerprint_matches", 0) >= 1
        chaos = rep.get("chaos") or {}
        events = chaos.get("events") or []
        assert len(events) == expect_events
        assert not any(ev.get("error") for ev in events), events
        kinds = {ev["kind"] for ev in events}
        assert kinds == {"partition", "kill"}
        kill = next(ev for ev in events if ev["kind"] == "kill")
        assert kill.get("restarted_after_s") is not None
        assert chaos.get("unrecovered") == 0, events
        integ = rep["integrity"]
        assert integ["overplaced_jobs"] == 0
        assert integ["duplicate_alloc_names"] == 0
        assert integ["overcommitted_nodes"] == 0
        assert rep["sustained"]["stragglers_after_drain"] == 0
        # The satellite proof: no hot method on the msgpack fallback.
        assert (rep.get("codec") or {}).get("hot_msgpack_methods") == {}

    def test_smoke_soak_fixed_seed_zero_violations(self):
        """The tier-1 chaos gate: one split/heal cycle plus one REAL
        subprocess SIGKILL+restart (recovering from the follower's own
        raft store) under bounded offered load, with the continuous
        auditor asserting every invariant live — zero violations, zero
        stragglers, recovery inside the bound."""
        from nomad_tpu.loadgen.harness import run_scenario
        from nomad_tpu.loadgen.scenario import get_scenario

        rep = run_scenario(get_scenario("chaos_smoke"))
        self._assert_clean(rep, expect_events=2)

    @pytest.mark.slow
    def test_full_soak_three_servers(self):
        """The recorded chaos_soak shape (LOADGEN_r05.json): 3 servers,
        kills + repeated partitions, zero violations."""
        from nomad_tpu.loadgen.harness import run_scenario
        from nomad_tpu.loadgen.scenario import get_scenario

        rep = run_scenario(get_scenario("chaos_soak"))
        self._assert_clean(rep, expect_events=3)
