"""Multi-region federation tests (reference: nomad/rpc.go:263
forwardRegion, nomad/serf.go WAN gossip): regions federate through WAN
membership; requests targeting another region route to a server there;
WAN members never join the local region's raft quorum."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import structs as s


def wait_until(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def federation(tmp_path):
    """One single-voter server per region, WAN-joined."""
    global_srv = Server(ServerConfig(
        region="global", node_name="global-1", enable_rpc=True,
        num_schedulers=1))
    global_srv.start()
    eu_srv = Server(ServerConfig(
        region="eu", node_name="eu-1", enable_rpc=True,
        num_schedulers=1,
        wan_join=[global_srv.config.rpc_advertise]))
    eu_srv.start()
    yield global_srv, eu_srv
    eu_srv.shutdown()
    global_srv.shutdown()


def make_job(region):
    job = mock.job()
    job.region = region
    job.task_groups[0].count = 1
    for t in job.task_groups[0].tasks:
        t.resources.networks = []
    return job


class TestFederation:
    def test_wan_membership_and_regions(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        assert wait_until(lambda: len(eu_srv.members()) == 2)
        assert global_srv.regions() == ["eu", "global"]
        assert eu_srv.regions() == ["eu", "global"]
        # Both remain leaders of their own (single-voter) regions.
        assert global_srv.is_leader() and eu_srv.is_leader()

    def test_wan_members_not_in_raft_quorum(self, tmp_path):
        """A multi-server region federated over WAN must keep only its own
        region's servers as voters."""
        s1 = Server(ServerConfig(
            region="global", node_name="g1", enable_rpc=True,
            data_dir=str(tmp_path / "g1"), bootstrap_expect=2,
            num_schedulers=0))
        s1.start()
        s2 = Server(ServerConfig(
            region="global", node_name="g2", enable_rpc=True,
            data_dir=str(tmp_path / "g2"), bootstrap_expect=2,
            start_join=[s1.config.rpc_advertise], num_schedulers=0))
        s2.start()
        eu = Server(ServerConfig(
            region="eu", node_name="eu1", enable_rpc=True,
            num_schedulers=0, wan_join=[s1.config.rpc_advertise]))
        eu.start()
        try:
            assert wait_until(lambda: any(
                srv.is_leader() for srv in (s1, s2)), 20.0)
            assert wait_until(lambda: len(s1.members()) == 3)
            leader = s1 if s1.is_leader() else s2
            # Voter set stays the two global servers, never the eu member.
            peers = set(leader.raft.peers)
            assert peers == {s1.config.rpc_advertise,
                             s2.config.rpc_advertise}, peers
        finally:
            eu.shutdown()
            s2.shutdown()
            s1.shutdown()

    def test_job_routes_to_its_region(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)

        job = make_job("eu")
        index, eval_id = global_srv.job_register(job)
        assert eval_id
        # The job lives in the eu region's state, not global's.
        assert eu_srv.state.job_by_id(None, job.id) is not None
        assert global_srv.state.job_by_id(None, job.id) is None

        # And it schedules there once eu has capacity.
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        eu_srv.node_register(node)
        assert wait_until(lambda: len(
            eu_srv.state.allocs_by_job(None, job.id, True)) == 1)

        # Deregister routed the same way.
        global_srv.job_deregister(job.id, purge=False, region="eu")
        assert wait_until(lambda: eu_srv.state.job_by_id(
            None, job.id).stop is True)

    def test_http_region_param_routes(self, federation, tmp_path):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig
        from nomad_tpu.api.client import NomadAPI, QueryOptions

        # HTTP agent fronting the *global* server: point its server block
        # at the running global server via an in-process shim is complex;
        # instead drive the global server's own HTTP by building an agent
        # around a fresh server in region 'global' WAN-joined to eu.
        cfg = AgentConfig()
        cfg.name = "g-http"
        cfg.server.enabled = True
        cfg.ports.http = 0
        cfg.ports.rpc = 0
        cfg.server.wan_join = [eu_srv.config.rpc_advertise]
        agent = Agent(cfg)
        agent.start()
        try:
            assert wait_until(lambda: "eu" in agent.server.regions())
            api = NomadAPI(address=agent.http.address, region="eu")
            job = make_job("eu")
            job.id = job.name = "http-routed"
            resp, _ = api.jobs.register(job)
            assert resp["EvalID"]
            assert wait_until(lambda: eu_srv.state.job_by_id(
                None, "http-routed") is not None)
            assert agent.server.state.job_by_id(None, "http-routed") is None
            # /v1/regions lists the federation.
            import json
            import urllib.request
            with urllib.request.urlopen(
                    agent.http.address + "/v1/regions") as r:
                regions = json.loads(r.read())
            assert regions == ["eu", "global"]
        finally:
            agent.shutdown()

    def test_unknown_region_semantics(self, federation):
        global_srv, _ = federation
        # An EXPLICITLY requested unknown region is an error…
        job = make_job("mars")
        with pytest.raises(ValueError):
            global_srv.job_register(job, region="mars")
        # …but a job-file region that is not federated registers locally
        # (a renamed single-region cluster still accepts default-region
        # job files).
        job2 = make_job("mars")
        index, eval_id = global_srv.job_register(job2)
        assert eval_id
        assert global_srv.state.job_by_id(None, job2.id) is not None


class TestRegionReads:
    def test_job_list_and_get_route(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        job = make_job("eu")
        job.id = job.name = "read-routed"
        global_srv.job_register(job)
        assert wait_until(lambda: eu_srv.state.job_by_id(
            None, "read-routed") is not None)
        # Reads against the GLOBAL server route to eu when asked to
        # (rpc.go:178 forwards reads too).
        got = global_srv.job_get("read-routed", region="eu")
        assert got is not None and got.id == "read-routed"
        listed, _idx = global_srv.job_list(prefix="read-", region="eu")
        assert [j.id for j in listed] == ["read-routed"]
        assert global_srv.job_get("read-routed") is None


@pytest.mark.slow
class TestMultiSliceMesh:
    """The device-level twin of multi-region federation (SURVEY §2.9
    last row, VERDICT r4 #4): each region's server owns its OWN device
    mesh — a disjoint slice of the 8 virtual CPU devices — and its batch
    scheduler runs the placement loop node-sharded over that mesh
    (ops/batch_sched._dispatch_mesh → parallel/sharded.py).  A job
    targeting region B submitted to region A forwards host-side
    (rpc.go:263) and schedules on B's mesh."""

    def test_two_meshes_cross_region(self):
        import jax

        from nomad_tpu.ops import batch_sched
        from nomad_tpu.parallel import make_node_mesh

        devs = jax.devices()
        assert len(devs) >= 8, "conftest must provide the 8-device CPU mesh"
        mesh_a = make_node_mesh(devs[:4])
        mesh_b = make_node_mesh(devs[4:8])

        global_srv = Server(ServerConfig(
            region="global", node_name="global-mesh-1", enable_rpc=True,
            num_schedulers=1, use_tpu_batch_worker=True,
            device_mesh=mesh_a))
        global_srv.start()
        eu_srv = Server(ServerConfig(
            region="eu", node_name="eu-mesh-1", enable_rpc=True,
            num_schedulers=1, use_tpu_batch_worker=True,
            device_mesh=mesh_b,
            wan_join=[global_srv.config.rpc_advertise]))
        eu_srv.start()
        try:
            assert wait_until(lambda: len(global_srv.members()) == 2)

            for _ in range(4):
                node = mock.node()
                node.resources.networks = []
                node.reserved.networks = []
                eu_srv.node_register(node)

            passes_before = batch_sched.MESH_PASSES
            job = make_job("eu")
            job.task_groups[0].count = 6
            index, eval_id = global_srv.job_register(job)
            assert eval_id
            # Forwarded: the job lives in eu's state, not global's.
            assert eu_srv.state.job_by_id(None, job.id) is not None
            assert global_srv.state.job_by_id(None, job.id) is None

            # Scheduled on B's mesh: all 6 allocs placed...
            assert wait_until(lambda: len(
                eu_srv.state.allocs_by_job(None, job.id, True)) == 6,
                timeout=60.0)
            # ...by a mesh placement pass, not the single-chip path.
            assert batch_sched.MESH_PASSES > passes_before
            # Placements verified: every alloc on a registered eu node,
            # anti-affinity spread across the 4 nodes (count 6 on 4
            # nodes → max 2 per node), no overcommit.
            allocs = eu_srv.state.allocs_by_job(None, job.id, True)
            per_node = {}
            for a in allocs:
                assert eu_srv.state.node_by_id(None, a.node_id) is not None
                per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
            assert max(per_node.values()) <= 2 and len(per_node) == 4
        finally:
            eu_srv.shutdown()
            global_srv.shutdown()
