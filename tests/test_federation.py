"""Multi-region federation tests (reference: nomad/rpc.go:263
forwardRegion, nomad/serf.go WAN gossip): regions federate through WAN
membership; requests targeting another region route to a server there;
WAN members never join the local region's raft quorum."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import structs as s


def wait_until(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def federation(tmp_path):
    """One single-voter server per region, WAN-joined."""
    global_srv = Server(ServerConfig(
        region="global", node_name="global-1", enable_rpc=True,
        num_schedulers=1))
    global_srv.start()
    eu_srv = Server(ServerConfig(
        region="eu", node_name="eu-1", enable_rpc=True,
        num_schedulers=1,
        wan_join=[global_srv.config.rpc_advertise]))
    eu_srv.start()
    yield global_srv, eu_srv
    eu_srv.shutdown()
    global_srv.shutdown()


def make_job(region):
    job = mock.job()
    job.region = region
    job.task_groups[0].count = 1
    for t in job.task_groups[0].tasks:
        t.resources.networks = []
    return job


class TestFederation:
    def test_wan_membership_and_regions(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        assert wait_until(lambda: len(eu_srv.members()) == 2)
        assert global_srv.regions() == ["eu", "global"]
        assert eu_srv.regions() == ["eu", "global"]
        # Both remain leaders of their own (single-voter) regions.
        assert global_srv.is_leader() and eu_srv.is_leader()

    def test_wan_members_not_in_raft_quorum(self, tmp_path):
        """A multi-server region federated over WAN must keep only its own
        region's servers as voters."""
        s1 = Server(ServerConfig(
            region="global", node_name="g1", enable_rpc=True,
            data_dir=str(tmp_path / "g1"), bootstrap_expect=2,
            num_schedulers=0))
        s1.start()
        s2 = Server(ServerConfig(
            region="global", node_name="g2", enable_rpc=True,
            data_dir=str(tmp_path / "g2"), bootstrap_expect=2,
            start_join=[s1.config.rpc_advertise], num_schedulers=0))
        s2.start()
        eu = Server(ServerConfig(
            region="eu", node_name="eu1", enable_rpc=True,
            num_schedulers=0, wan_join=[s1.config.rpc_advertise]))
        eu.start()
        try:
            assert wait_until(lambda: any(
                srv.is_leader() for srv in (s1, s2)), 20.0)
            assert wait_until(lambda: len(s1.members()) == 3)
            leader = s1 if s1.is_leader() else s2
            # Voter set stays the two global servers, never the eu member.
            peers = set(leader.raft.peers)
            assert peers == {s1.config.rpc_advertise,
                             s2.config.rpc_advertise}, peers
        finally:
            eu.shutdown()
            s2.shutdown()
            s1.shutdown()

    def test_job_routes_to_its_region(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)

        job = make_job("eu")
        index, eval_id = global_srv.job_register(job)
        assert eval_id
        # The job lives in the eu region's state, not global's.
        assert eu_srv.state.job_by_id(None, job.id) is not None
        assert global_srv.state.job_by_id(None, job.id) is None

        # And it schedules there once eu has capacity.
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        eu_srv.node_register(node)
        assert wait_until(lambda: len(
            eu_srv.state.allocs_by_job(None, job.id, True)) == 1)

        # Deregister routed the same way.
        global_srv.job_deregister(job.id, purge=False, region="eu")
        assert wait_until(lambda: eu_srv.state.job_by_id(
            None, job.id).stop is True)

    def test_http_region_param_routes(self, federation, tmp_path):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig
        from nomad_tpu.api.client import NomadAPI, QueryOptions

        # HTTP agent fronting the *global* server: point its server block
        # at the running global server via an in-process shim is complex;
        # instead drive the global server's own HTTP by building an agent
        # around a fresh server in region 'global' WAN-joined to eu.
        cfg = AgentConfig()
        cfg.name = "g-http"
        cfg.server.enabled = True
        cfg.ports.http = 0
        cfg.ports.rpc = 0
        cfg.server.wan_join = [eu_srv.config.rpc_advertise]
        agent = Agent(cfg)
        agent.start()
        try:
            assert wait_until(lambda: "eu" in agent.server.regions())
            api = NomadAPI(address=agent.http.address, region="eu")
            job = make_job("eu")
            job.id = job.name = "http-routed"
            resp, _ = api.jobs.register(job)
            assert resp["EvalID"]
            assert wait_until(lambda: eu_srv.state.job_by_id(
                None, "http-routed") is not None)
            assert agent.server.state.job_by_id(None, "http-routed") is None
            # /v1/regions lists the federation.
            import json
            import urllib.request
            with urllib.request.urlopen(
                    agent.http.address + "/v1/regions") as r:
                regions = json.loads(r.read())
            assert regions == ["eu", "global"]
            # ?detail=1 adds server counts and a resolved leader for
            # BOTH the home region and the remote one (the remote
            # leader comes from a live Status.Leader probe).
            with urllib.request.urlopen(
                    agent.http.address + "/v1/regions?detail=1") as r:
                detail = json.loads(r.read())
            assert [d["Name"] for d in detail] == ["eu", "global"]
            by_name = {d["Name"]: d for d in detail}
            assert by_name["eu"]["Servers"] == 1
            assert by_name["eu"]["Leader"] == \
                eu_srv.config.rpc_advertise, detail
            assert by_name["global"]["Leader"] == \
                agent.server.config.rpc_advertise, detail
        finally:
            agent.shutdown()

    def test_unknown_region_semantics(self, federation):
        global_srv, _ = federation
        # An EXPLICITLY requested unknown region is an error…
        job = make_job("mars")
        with pytest.raises(ValueError):
            global_srv.job_register(job, region="mars")
        # …but a job-file region that is not federated registers locally
        # (a renamed single-region cluster still accepts default-region
        # job files).
        job2 = make_job("mars")
        index, eval_id = global_srv.job_register(job2)
        assert eval_id
        assert global_srv.state.job_by_id(None, job2.id) is not None


class TestRegionReads:
    def test_job_list_and_get_route(self, federation):
        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        job = make_job("eu")
        job.id = job.name = "read-routed"
        global_srv.job_register(job)
        assert wait_until(lambda: eu_srv.state.job_by_id(
            None, "read-routed") is not None)
        # Reads against the GLOBAL server route to eu when asked to
        # (rpc.go:178 forwards reads too).
        got = global_srv.job_get("read-routed", region="eu")
        assert got is not None and got.id == "read-routed"
        listed, _idx = global_srv.job_list(prefix="read-", region="eu")
        assert [j.id for j in listed] == ["read-routed"]
        assert global_srv.job_get("read-routed") is None


@pytest.mark.slow
class TestMultiSliceMesh:
    """The device-level twin of multi-region federation (SURVEY §2.9
    last row, VERDICT r4 #4): each region's server owns its OWN device
    mesh — a disjoint slice of the 8 virtual CPU devices — and its batch
    scheduler runs the placement loop node-sharded over that mesh
    (ops/batch_sched._dispatch_mesh → parallel/sharded.py).  A job
    targeting region B submitted to region A forwards host-side
    (rpc.go:263) and schedules on B's mesh."""

    def test_two_meshes_cross_region(self):
        import jax

        from nomad_tpu.ops import batch_sched
        from nomad_tpu.parallel import make_node_mesh

        devs = jax.devices()
        assert len(devs) >= 8, "conftest must provide the 8-device CPU mesh"
        mesh_a = make_node_mesh(devs[:4])
        mesh_b = make_node_mesh(devs[4:8])

        global_srv = Server(ServerConfig(
            region="global", node_name="global-mesh-1", enable_rpc=True,
            num_schedulers=1, use_tpu_batch_worker=True,
            device_mesh=mesh_a))
        global_srv.start()
        eu_srv = Server(ServerConfig(
            region="eu", node_name="eu-mesh-1", enable_rpc=True,
            num_schedulers=1, use_tpu_batch_worker=True,
            device_mesh=mesh_b,
            wan_join=[global_srv.config.rpc_advertise]))
        eu_srv.start()
        try:
            assert wait_until(lambda: len(global_srv.members()) == 2)

            for _ in range(4):
                node = mock.node()
                node.resources.networks = []
                node.reserved.networks = []
                eu_srv.node_register(node)

            passes_before = batch_sched.MESH_PASSES
            job = make_job("eu")
            job.task_groups[0].count = 6
            index, eval_id = global_srv.job_register(job)
            assert eval_id
            # Forwarded: the job lives in eu's state, not global's.
            assert eu_srv.state.job_by_id(None, job.id) is not None
            assert global_srv.state.job_by_id(None, job.id) is None

            # Scheduled on B's mesh: all 6 allocs placed...
            assert wait_until(lambda: len(
                eu_srv.state.allocs_by_job(None, job.id, True)) == 6,
                timeout=60.0)
            # ...by a mesh placement pass, not the single-chip path.
            assert batch_sched.MESH_PASSES > passes_before
            # Placements verified: every alloc on a registered eu node,
            # anti-affinity spread across the 4 nodes (count 6 on 4
            # nodes → max 2 per node), no overcommit.
            allocs = eu_srv.state.allocs_by_job(None, job.id, True)
            per_node = {}
            for a in allocs:
                assert eu_srv.state.node_by_id(None, a.node_id) is not None
                per_node[a.node_id] = per_node.get(a.node_id, 0) + 1
            assert max(per_node.values()) <= 2 and len(per_node) == 4
        finally:
            eu_srv.shutdown()
            global_srv.shutdown()


class TestNoPathToRegionWire:
    def test_from_message_round_trip(self):
        from nomad_tpu.server.rpc import NoPathToRegion

        orig = NoPathToRegion("eu", 2.5, rounds=3, detail="2 dials failed")
        back = NoPathToRegion.from_message(str(orig))
        assert back.region == "eu"
        assert back.retry_after == 2.5
        assert back.rounds == 3

    def test_from_message_defaults_on_garbage(self):
        from nomad_tpu.server.rpc import NoPathToRegion

        back = NoPathToRegion.from_message("mangled wire error")
        assert back.region == ""
        assert back.retry_after > 0


@pytest.mark.federation
class TestRegionPartition:
    """The ISSUE 17 robustness contract, unit-sized: severing a region
    mid-submit yields a typed retryable error (never a hang, never a
    lost eval), and after heal the job places exactly once, on the
    owning region only."""

    def test_sever_mid_submit_is_retryable_then_heals(self, federation):
        from nomad_tpu import fault
        from nomad_tpu.server.rpc import NoPathToRegion

        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        eu_srv.node_register(node)

        region_addrs = {"global": [global_srv.config.rpc_advertise],
                        "eu": [eu_srv.config.rpc_advertise]}
        job = make_job("eu")
        try:
            fault.net_sever_regions(region_addrs, isolate="eu",
                                    name="t-fed-sever")
            t0 = time.monotonic()
            with pytest.raises(NoPathToRegion) as exc:
                global_srv.job_register(job, region="eu")
            # Typed, bounded, and honest about where it failed: the
            # submit degraded in bounded time with a retry hint — it
            # did not hang on the dark region.
            assert exc.value.region == "eu"
            assert exc.value.retry_after > 0
            assert exc.value.rounds >= 1
            assert time.monotonic() - t0 < 15.0
            # Nothing was ever sent: the job landed in NEITHER region.
            assert global_srv.state.job_by_id(None, job.id) is None
            assert eu_srv.state.job_by_id(None, job.id) is None

            fault.net_heal("t-fed-sever")

            # The client retry loop the error contract promises: the
            # SAME submit eventually goes through after heal (the dial
            # gate's per-address backoff may reject the first try).
            def resubmit():
                try:
                    _, eval_id = global_srv.job_register(job, region="eu")
                    return bool(eval_id)
                except NoPathToRegion:
                    return False

            assert wait_until(resubmit, timeout=15.0)
            # Exactly-once placement on the owning region only.
            assert wait_until(lambda: len(
                eu_srv.state.allocs_by_job(None, job.id, True)) == 1)
            time.sleep(0.3)
            assert len(eu_srv.state.allocs_by_job(None, job.id, True)) == 1
            assert global_srv.state.job_by_id(None, job.id) is None
            assert len(
                global_srv.state.allocs_by_job(None, job.id, True)) == 0
        finally:
            fault.net_disarm()


@pytest.mark.federation
class TestRegionEventAggregator:
    def test_fan_in_tags_and_cursors(self, federation):
        from nomad_tpu.server.federation import RegionEventAggregator
        from nomad_tpu.server.rpc import ConnPool

        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        # Arm both regions' event brokers the in-process way.
        subs = [srv.event_stream_subscribe(topics={"Job": set()})
                for srv in (global_srv, eu_srv)]
        pool = ConnPool()
        agg = RegionEventAggregator(
            {"global": global_srv.config.rpc_advertise,
             "eu": eu_srv.config.rpc_advertise}, pool=pool)
        try:
            g_job = make_job("global")
            g_job.id = g_job.name = "agg-global"
            global_srv.job_register(g_job)
            e_job = make_job("eu")
            e_job.id = e_job.name = "agg-eu"
            eu_srv.job_register(e_job)

            seen = []

            def both_regions_seen():
                seen.extend(agg.poll())
                return {"global", "eu"} <= {ev["Region"] for ev in seen}

            assert wait_until(both_regions_seen, timeout=10.0)
            # Every event is region-tagged and carries its region-local
            # index; the fan-in never duplicates (cursor contract).
            keys = [(ev["Region"], ev["Index"], ev.get("Topic"),
                     ev.get("Type"), ev.get("Key")) for ev in seen]
            assert len(keys) == len(set(keys))
            cursors = agg.cursors()
            assert cursors["global"] > 0 and cursors["eu"] > 0
            assert agg.stats()["Events"] == len(seen)
        finally:
            pool.close()
            for sub in subs:
                sub.close()

    def test_dark_region_skipped_cursor_intact(self, federation):
        from nomad_tpu import fault
        from nomad_tpu.server.federation import RegionEventAggregator
        from nomad_tpu.server.rpc import ConnPool

        global_srv, eu_srv = federation
        assert wait_until(lambda: len(global_srv.members()) == 2)
        subs = [srv.event_stream_subscribe(topics={"Job": set()})
                for srv in (global_srv, eu_srv)]
        pool = ConnPool()
        agg = RegionEventAggregator(
            {"global": global_srv.config.rpc_advertise,
             "eu": eu_srv.config.rpc_advertise}, pool=pool)
        try:
            e_job = make_job("eu")
            e_job.id = e_job.name = "agg-dark-1"
            eu_srv.job_register(e_job)
            assert wait_until(
                lambda: any(ev["Region"] == "eu" for ev in agg.poll()),
                timeout=10.0)
            cursor_before = agg.cursors()["eu"]

            fault.net_sever_regions(
                {"global": [global_srv.config.rpc_advertise],
                 "eu": [eu_srv.config.rpc_advertise]},
                isolate="eu", name="t-agg-dark")
            # While dark: the poll round completes (never hangs), eu is
            # reported unreachable, and its cursor does not move.
            agg.poll()
            assert "eu" in agg.unreachable()
            assert agg.cursors()["eu"] == cursor_before

            fault.net_heal("t-agg-dark")
            e2 = make_job("eu")
            e2.id = e2.name = "agg-dark-2"
            eu_srv.job_register(e2)

            resumed = []

            def eu_resumes():
                resumed.extend(
                    ev for ev in agg.poll() if ev["Region"] == "eu")
                return any(ev.get("Key") == "agg-dark-2" or
                           "agg-dark-2" in str(ev.get("Payload", ""))
                           for ev in resumed)

            assert wait_until(eu_resumes, timeout=10.0)
            # No gap, no duplicate: everything eu emitted past the
            # pre-dark cursor arrives exactly once, in index order
            # (one raft apply may emit several events at ONE index, so
            # uniqueness is per event, not per index).
            idxs = [ev["Index"] for ev in resumed]
            assert idxs == sorted(idxs)
            keys = [(ev["Index"], ev.get("Topic"), ev.get("Type"),
                     ev.get("Key")) for ev in resumed]
            assert len(keys) == len(set(keys))
            assert all(i > cursor_before for i in idxs)
        finally:
            fault.net_disarm()
            pool.close()
            for sub in subs:
                sub.close()
