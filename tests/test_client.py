"""Client runtime tests: restart tracker, task env, drivers, task/alloc
runners, allocdir, getter, GC (reference: client/*_test.go)."""
import os
import signal
import sys
import tempfile
import time

import pytest

from nomad_tpu.structs import structs as s
from nomad_tpu import mock
from nomad_tpu.client import (
    AllocRunner,
    ClientConfig,
    RestartTracker,
    TaskRunner,
    get_client_status,
)
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.driver import env as envmod
from nomad_tpu.client.driver.driver import (
    DriverError,
    RecoverableError,
    WaitResult,
)
from nomad_tpu.client.gc import AllocGarbageCollector
from nomad_tpu.client.getter import ArtifactError, get_artifact
from nomad_tpu.client.restarts import (
    REASON_NO_RESTARTS_ALLOWED,
    REASON_UNRECOVERABLE,
    REASON_WITHIN_POLICY,
)


def wait_until(pred, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# RestartTracker (client/restarts_test.go)


def policy(attempts=2, interval=60.0, delay=0.01, mode=s.RESTART_POLICY_MODE_DELAY):
    return s.RestartPolicy(attempts=attempts, interval=interval, delay=delay,
                           mode=mode)


class TestRestartTracker:
    def test_service_restarts_on_success(self):
        rt = RestartTracker(policy(), s.JOB_TYPE_SERVICE)
        rt.set_wait_result(WaitResult(exit_code=0))
        state, _ = rt.get_state()
        assert state == s.TASK_RESTARTING
        assert rt.get_reason() == REASON_WITHIN_POLICY

    def test_batch_terminates_on_success(self):
        rt = RestartTracker(policy(), s.JOB_TYPE_BATCH)
        rt.set_wait_result(WaitResult(exit_code=0))
        state, _ = rt.get_state()
        assert state == s.TASK_TERMINATED

    def test_zero_attempts(self):
        rt = RestartTracker(policy(attempts=0), s.JOB_TYPE_SERVICE)
        rt.set_wait_result(WaitResult(exit_code=1))
        state, _ = rt.get_state()
        assert state == s.TASK_NOT_RESTARTING
        assert rt.get_reason() == REASON_NO_RESTARTS_ALLOWED

    def test_fail_mode_exhausts(self):
        rt = RestartTracker(policy(attempts=1, mode=s.RESTART_POLICY_MODE_FAIL),
                            s.JOB_TYPE_SERVICE)
        rt.set_wait_result(WaitResult(exit_code=1))
        assert rt.get_state()[0] == s.TASK_RESTARTING
        rt.set_wait_result(WaitResult(exit_code=1))
        assert rt.get_state()[0] == s.TASK_NOT_RESTARTING

    def test_delay_mode_waits_out_interval(self):
        rt = RestartTracker(policy(attempts=1, interval=5.0), s.JOB_TYPE_SERVICE)
        rt.set_wait_result(WaitResult(exit_code=1))
        rt.get_state()
        rt.set_wait_result(WaitResult(exit_code=1))
        state, delay = rt.get_state()
        assert state == s.TASK_RESTARTING
        assert delay > 1.0  # remainder of the 5s interval

    def test_unrecoverable_start_error(self):
        rt = RestartTracker(policy(), s.JOB_TYPE_SERVICE)
        rt.set_start_error(DriverError("bad config"))
        state, _ = rt.get_state()
        assert state == s.TASK_NOT_RESTARTING
        assert rt.get_reason() == REASON_UNRECOVERABLE

    def test_recoverable_start_error_restarts(self):
        rt = RestartTracker(policy(), s.JOB_TYPE_SERVICE)
        rt.set_start_error(RecoverableError("transient"))
        state, _ = rt.get_state()
        assert state == s.TASK_RESTARTING

    def test_restart_triggered(self):
        rt = RestartTracker(policy(attempts=0), s.JOB_TYPE_SERVICE)
        rt.set_restart_triggered()
        state, delay = rt.get_state()
        assert state == s.TASK_RESTARTING and delay == 0.0

    def test_interval_reset(self):
        rt = RestartTracker(policy(attempts=1, interval=0.05), s.JOB_TYPE_SERVICE)
        rt.set_wait_result(WaitResult(exit_code=1))
        assert rt.get_state()[0] == s.TASK_RESTARTING
        time.sleep(0.06)
        rt.set_wait_result(WaitResult(exit_code=1))
        assert rt.get_state()[0] == s.TASK_RESTARTING  # budget reset


# ---------------------------------------------------------------------------
# Task env builder (client/driver/env/env_test.go)


class TestTaskEnv:
    def build_env(self):
        alloc = mock.alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.env = {"CUSTOM": "x-${NOMAD_TASK_NAME}", "NODE_DC": "${node.datacenter}"}
        node = mock.node()
        b = envmod.Builder()
        b.set_task(task).set_alloc(alloc).set_node(node).set_region("global")
        b.set_dirs("/a/alloc", "/a/web/local", "/a/web/secrets")
        return b.build(), alloc, task, node

    def test_standard_vars(self):
        env, alloc, task, node = self.build_env()
        m = env.env()
        assert m["NOMAD_ALLOC_DIR"] == "/a/alloc"
        assert m["NOMAD_TASK_DIR"] == "/a/web/local"
        assert m["NOMAD_SECRETS_DIR"] == "/a/web/secrets"
        assert m["NOMAD_ALLOC_ID"] == alloc.id
        assert m["NOMAD_TASK_NAME"] == task.name
        assert m["NOMAD_JOB_NAME"] == alloc.job.name
        assert m["NOMAD_DC"] == node.datacenter
        assert m["NOMAD_REGION"] == "global"
        assert m["NOMAD_CPU_LIMIT"] == str(task.resources.cpu)
        assert m["NOMAD_MEMORY_LIMIT"] == str(task.resources.memory_mb)

    def test_task_env_interpolation(self):
        env, _, task, node = self.build_env()
        m = env.env()
        assert m["CUSTOM"] == f"x-{task.name}"
        assert m["NODE_DC"] == node.datacenter

    def test_replace_env(self):
        env, _, _, node = self.build_env()
        assert env.replace_env("${node.datacenter}-suffix") == \
            f"{node.datacenter}-suffix"
        assert env.replace_env("${missing.var}") == ""

    def test_alloc_index(self):
        env, alloc, _, _ = self.build_env()
        # mock alloc name is "web[0]"-ish; index parsed from the name
        if "[" in alloc.name:
            want = alloc.name.rsplit("[", 1)[1].rstrip("]")
            assert env.env()["NOMAD_ALLOC_INDEX"] == want

    def test_port_env(self):
        alloc = mock.alloc()
        task = alloc.job.task_groups[0].tasks[0]
        res = (alloc.task_resources or {}).get(task.name)
        if res is None or not res.networks:
            pytest.skip("mock alloc has no task networks")
        b = envmod.Builder()
        b.set_task(task).set_alloc(alloc)
        m = b.build().env()
        net = res.networks[0]
        for label, port in net.port_labels().items():
            assert m[f"NOMAD_PORT_{label}"] == str(port)
            assert m[f"NOMAD_ADDR_{label}"] == f"{net.ip}:{port}"


# ---------------------------------------------------------------------------
# Alloc dir


class TestAllocDir:
    def test_build_layout(self, tmp_path):
        ad = AllocDir(str(tmp_path / "a1"))
        ad.build()
        td = ad.new_task_dir("web")
        td.build()
        assert os.path.isdir(os.path.join(ad.shared_dir, "data"))
        assert os.path.isdir(os.path.join(ad.shared_dir, "logs"))
        assert os.path.isdir(td.local_dir)
        assert os.path.isdir(td.secrets_dir)

    def test_move_sticky(self, tmp_path):
        old = AllocDir(str(tmp_path / "old"))
        old.build()
        old.new_task_dir("web").build()
        with open(os.path.join(old.shared_dir, "data", "state.bin"), "w") as f:
            f.write("persisted")
        with open(os.path.join(old.task_dirs["web"].local_dir, "cache"), "w") as f:
            f.write("warm")

        new = AllocDir(str(tmp_path / "new"))
        new.build()
        new.new_task_dir("web").build()
        new.move(old, ["web"])
        assert open(os.path.join(new.shared_dir, "data", "state.bin")).read() \
            == "persisted"
        assert open(os.path.join(new.task_dirs["web"].local_dir, "cache")).read() \
            == "warm"

    def test_snapshot_restore(self, tmp_path):
        src = AllocDir(str(tmp_path / "src"))
        src.build()
        src.new_task_dir("web").build()
        with open(os.path.join(src.shared_dir, "data", "f"), "w") as f:
            f.write("snap")
        blob = src.snapshot()

        dst = AllocDir(str(tmp_path / "dst"))
        dst.build()
        dst.new_task_dir("web").build()
        dst.restore_snapshot(blob)
        assert open(os.path.join(dst.shared_dir, "data", "f")).read() == "snap"

    def test_path_escape_rejected(self, tmp_path):
        ad = AllocDir(str(tmp_path / "a"))
        ad.build()
        with pytest.raises(PermissionError):
            ad.read_at("../../etc/passwd", 0, 10)


# ---------------------------------------------------------------------------
# Artifact getter


class TestGetter:
    def test_file_artifact(self, tmp_path):
        src = tmp_path / "artifact.txt"
        src.write_text("payload")
        task_dir = tmp_path / "task"
        task_dir.mkdir()
        art = s.TaskArtifact(getter_source=f"file://{src}", relative_dest="local/")
        env = envmod.TaskEnv()
        dest = get_artifact(env, art, str(task_dir))
        assert open(dest).read() == "payload"

    def test_checksum_mismatch(self, tmp_path):
        src = tmp_path / "artifact.txt"
        src.write_text("payload")
        task_dir = tmp_path / "task"
        task_dir.mkdir()
        art = s.TaskArtifact(getter_source=str(src), relative_dest="local/",
                             getter_options={"checksum": "sha256:" + "0" * 64})
        with pytest.raises(ArtifactError):
            get_artifact(envmod.TaskEnv(), art, str(task_dir))

    def test_interpolated_source(self, tmp_path):
        src = tmp_path / "artifact.txt"
        src.write_text("x")
        task_dir = tmp_path / "task"
        task_dir.mkdir()
        env = envmod.TaskEnv(env_map={"SRC": str(src)})
        art = s.TaskArtifact(getter_source="${SRC}", relative_dest="local/")
        assert os.path.exists(get_artifact(env, art, str(task_dir)))

    def test_s3_artifact_anonymous_and_signed(self, tmp_path, monkeypatch):
        """s3:: endpoint form against a local fake bucket: anonymous GET,
        then a SigV4-signed GET once AWS creds are in the environment
        (getter.go s3 support)."""
        import http.server
        import threading

        seen = {}

        class FakeS3(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                seen["path"] = self.path
                seen["auth"] = self.headers.get("Authorization", "")
                seen["sha"] = self.headers.get("x-amz-content-sha256", "")
                body = b"s3-object-bytes"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeS3)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        try:
            task_dir = tmp_path / "task"
            task_dir.mkdir()
            art = s.TaskArtifact(
                getter_source=f"s3::http://127.0.0.1:{port}/bkt/obj.bin",
                relative_dest="local/")
            dest = get_artifact(envmod.TaskEnv(), art, str(task_dir))
            assert open(dest, "rb").read() == b"s3-object-bytes"
            assert seen["path"] == "/bkt/obj.bin"
            assert seen["auth"] == ""  # anonymous without creds

            monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDEXAMPLE")
            monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secret")
            dest = get_artifact(envmod.TaskEnv(), art, str(task_dir))
            assert seen["auth"].startswith("AWS4-HMAC-SHA256 Credential="
                                           "AKIDEXAMPLE/")
            assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" \
                in seen["auth"]
            assert seen["sha"] == (
                "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b"
                "7852b855")  # sha256 of empty body
        finally:
            httpd.shutdown()

    def test_s3_checksum_verified(self, tmp_path):
        import hashlib as hl
        import http.server
        import threading

        body = b"data-123"

        class FakeS3(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeS3)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        port = httpd.server_address[1]
        try:
            task_dir = tmp_path / "task"
            task_dir.mkdir()
            good = hl.sha256(body).hexdigest()
            art = s.TaskArtifact(
                getter_source=f"s3::http://127.0.0.1:{port}/b/k.bin",
                relative_dest="local/",
                getter_options={"checksum": f"sha256:{good}"})
            assert os.path.exists(
                get_artifact(envmod.TaskEnv(), art, str(task_dir)))
            art.getter_options = {"checksum": "sha256:" + "0" * 64}
            with pytest.raises(ArtifactError):
                get_artifact(envmod.TaskEnv(), art, str(task_dir))
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# Task runner + mock driver (client/task_runner_test.go)


def make_task_runner(tmp_path, config_overrides=None, job_type=s.JOB_TYPE_BATCH,
                     restart=None):
    alloc = mock.alloc()
    alloc.job.type = job_type
    tg = alloc.job.task_groups[0]
    tg.restart_policy = restart or s.RestartPolicy(
        attempts=0, mode=s.RESTART_POLICY_MODE_FAIL)
    task = tg.tasks[0]
    task.driver = "mock_driver"
    task.config = dict(config_overrides or {"run_for": "50ms"})

    ad = AllocDir(str(tmp_path / alloc.id))
    ad.build()
    td = ad.new_task_dir(task.name)
    td.build()

    updates = []

    def updater(name, state, event):
        updates.append((name, state, event))

    cfg = ClientConfig(alloc_dir=str(tmp_path))
    tr = TaskRunner(config=cfg, alloc=alloc, task=task, task_dir=td,
                    updater=updater, node=mock.node())
    return tr, updates


class TestTaskRunner:
    def test_simple_run_to_completion(self, tmp_path):
        tr, updates = make_task_runner(tmp_path)
        tr.run()
        assert tr.done.wait(5.0)
        states = [u[1] for u in updates if u[1]]
        assert states[0] == s.TASK_STATE_PENDING
        assert s.TASK_STATE_RUNNING in states
        assert states[-1] == s.TASK_STATE_DEAD
        events = [u[2].type for u in updates if u[2] is not None]
        assert s.TASK_RECEIVED in events
        assert s.TASK_STARTED in events
        assert s.TASK_TERMINATED in events

    def test_failed_exit_marks_failed(self, tmp_path):
        tr, updates = make_task_runner(
            tmp_path, {"run_for": "10ms", "exit_code": 1})
        tr.run()
        assert tr.done.wait(5.0)
        events = [u[2] for u in updates if u[2] is not None]
        assert any(e.type == s.TASK_NOT_RESTARTING and e.failed for e in events)

    def test_start_error(self, tmp_path):
        tr, updates = make_task_runner(tmp_path, {"start_error": "boom"})
        tr.run()
        assert tr.done.wait(5.0)
        events = [u[2].type for u in updates if u[2] is not None]
        assert s.TASK_DRIVER_FAILURE in events

    def test_restart_within_policy(self, tmp_path):
        tr, updates = make_task_runner(
            tmp_path, {"run_for": "10ms", "exit_code": 1},
            restart=s.RestartPolicy(attempts=1, interval=60.0, delay=0.01,
                                    mode=s.RESTART_POLICY_MODE_FAIL))
        tr.run()
        assert tr.done.wait(5.0)
        events = [u[2].type for u in updates if u[2] is not None]
        assert events.count(s.TASK_STARTED) == 2
        assert s.TASK_RESTARTING in events

    def test_destroy_kills(self, tmp_path):
        tr, updates = make_task_runner(tmp_path, {"run_for": "60s"})
        tr.run()
        assert wait_until(lambda: any(
            u[2] is not None and u[2].type == s.TASK_STARTED for u in updates))
        tr.destroy(s.TaskEvent(type=s.TASK_KILLED))
        assert tr.done.wait(5.0)
        events = [u[2].type for u in updates if u[2] is not None]
        assert s.TASK_KILLED in events


# ---------------------------------------------------------------------------
# Raw exec driver — real process

@pytest.mark.skipif(sys.platform != "linux", reason="linux-only")
class TestRawExec:
    def test_real_process(self, tmp_path):
        alloc = mock.alloc()
        alloc.job.type = s.JOB_TYPE_BATCH
        tg = alloc.job.task_groups[0]
        tg.restart_policy = s.RestartPolicy(attempts=0,
                                            mode=s.RESTART_POLICY_MODE_FAIL)
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {
            "command": sys.executable,
            "args": ["-c", "print('hello from ${NOMAD_TASK_NAME}')"],
        }
        ad = AllocDir(str(tmp_path / alloc.id))
        ad.build()
        td = ad.new_task_dir(task.name)
        td.build()

        updates = []
        cfg = ClientConfig(alloc_dir=str(tmp_path),
                           options={"driver.raw_exec.enable": "1"})
        tr = TaskRunner(config=cfg, alloc=alloc, task=task, task_dir=td,
                        updater=lambda n, st, ev: updates.append((n, st, ev)),
                        node=mock.node())
        tr.run()
        # Liveness bound, not a perf assertion: two python subprocesses
        # (supervisor + task) each pay the site hook's jax pre-import at
        # startup, which under full-suite load on 2 cores can exceed 10s.
        assert tr.done.wait(30.0)
        events = [u[2] for u in updates if u[2] is not None]
        term = [e for e in events if e.type == s.TASK_TERMINATED]
        assert term and term[0].exit_code == 0
        # stdout landed in the log dir with rotation naming
        logs = os.listdir(td.log_dir)
        stdout_logs = [f for f in logs if ".stdout." in f]
        assert stdout_logs
        content = open(os.path.join(td.log_dir, stdout_logs[0])).read()
        assert f"hello from {task.name}" in content


# ---------------------------------------------------------------------------
# Alloc runner (client/alloc_runner_test.go)


def make_alloc_runner(tmp_path, task_configs, job_type=s.JOB_TYPE_BATCH):
    """task_configs: dict task_name → mock driver config."""
    alloc = mock.alloc()
    alloc.job.type = job_type
    tg = alloc.job.task_groups[0]
    tg.restart_policy = s.RestartPolicy(attempts=0,
                                        mode=s.RESTART_POLICY_MODE_FAIL)
    base_task = tg.tasks[0]
    tg.tasks = []
    for name, cfg in task_configs.items():
        t = base_task.copy()
        t.name = name
        t.driver = "mock_driver"
        t.config = cfg
        tg.tasks.append(t)

    updates = []
    cfg = ClientConfig(alloc_dir=str(tmp_path))
    ar = AllocRunner(config=cfg, alloc=alloc,
                     updater=lambda a: updates.append(a), node=mock.node())
    return ar, updates


class TestAllocRunner:
    def test_single_task_complete(self, tmp_path):
        ar, updates = make_alloc_runner(tmp_path, {"web": {"run_for": "50ms"}})
        ar.run()
        assert ar.wait(5.0)
        assert wait_until(lambda: updates and updates[-1].client_status ==
                          s.ALLOC_CLIENT_STATUS_COMPLETE)

    def test_multi_task_running(self, tmp_path):
        ar, updates = make_alloc_runner(
            tmp_path, {"a": {"run_for": "30s"}, "b": {"run_for": "30s"}})
        ar.run()
        assert wait_until(lambda: updates and updates[-1].client_status ==
                          s.ALLOC_CLIENT_STATUS_RUNNING)
        ar.destroy()
        assert ar.wait(5.0)

    def test_failed_task_fails_alloc_and_kills_sibling(self, tmp_path):
        ar, updates = make_alloc_runner(
            tmp_path,
            {"bad": {"run_for": "10ms", "exit_code": 1},
             "good": {"run_for": "60s"}})
        ar.run()
        assert ar.wait(10.0)
        assert wait_until(lambda: updates and updates[-1].client_status ==
                          s.ALLOC_CLIENT_STATUS_FAILED)
        final = updates[-1]
        sibling_events = [e.type for e in final.task_states["good"].events]
        assert s.TASK_SIBLING_FAILED in sibling_events

    def test_get_client_status(self):
        ts = {"a": s.TaskState(state=s.TASK_STATE_RUNNING)}
        assert get_client_status(ts) == s.ALLOC_CLIENT_STATUS_RUNNING
        ts["b"] = s.TaskState(state=s.TASK_STATE_DEAD, failed=True)
        assert get_client_status(ts) == s.ALLOC_CLIENT_STATUS_FAILED
        assert get_client_status(
            {"a": s.TaskState(state=s.TASK_STATE_DEAD)}) == \
            s.ALLOC_CLIENT_STATUS_COMPLETE


# ---------------------------------------------------------------------------
# GC


class TestGC:
    def _terminal_runner(self, tmp_path, name):
        ar, _ = make_alloc_runner(tmp_path / name, {"t": {"run_for": "1ms"}})
        ar.run()
        ar.wait(5.0)
        return ar

    def test_make_room_for_evicts(self, tmp_path):
        cfg = ClientConfig(alloc_dir=str(tmp_path), gc_max_allocs=2)
        gc = AllocGarbageCollector(cfg, stats_path=str(tmp_path))
        r1 = self._terminal_runner(tmp_path, "a1")
        gc.mark_for_collection(r1)
        assert gc.count() == 1
        gc.make_room_for(0, total_live_allocs=2)
        assert gc.count() == 0

    def test_collect_all(self, tmp_path):
        cfg = ClientConfig(alloc_dir=str(tmp_path))
        gc = AllocGarbageCollector(cfg, stats_path=str(tmp_path))
        for n in ("a", "b"):
            gc.mark_for_collection(self._terminal_runner(tmp_path, n))
        assert gc.collect_all() == 2
        assert gc.count() == 0


class TestGitGetter:
    def test_git_clone_artifact(self, tmp_path):
        """go-getter git:: support (client/getter wraps go-getter)."""
        import subprocess

        from nomad_tpu.client.getter import get_artifact
        from nomad_tpu.client.driver.env import TaskEnv
        from nomad_tpu.structs import structs as s

        src_repo = tmp_path / "srcrepo"
        src_repo.mkdir()
        subprocess.run(["git", "init", "-q", str(src_repo)], check=True)
        (src_repo / "hello.txt").write_text("from git")
        env = {"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
        import os as _os
        subprocess.run(["git", "-C", str(src_repo), "add", "."], check=True)
        subprocess.run(["git", "-C", str(src_repo), "commit", "-q", "-m", "x"],
                       check=True, env={**_os.environ, **env})

        task_dir = tmp_path / "task"
        task_dir.mkdir()
        art = s.TaskArtifact(getter_source=f"git::file://{src_repo}",
                             relative_dest="local/")
        dest = get_artifact(TaskEnv(), art, str(task_dir))
        assert (pathlib_path := __import__("pathlib").Path(dest) / "hello.txt").exists()
        assert pathlib_path.read_text() == "from git"


class TestDriverFieldSchemas:
    """helper/fields FieldData.Validate role: typed driver-config
    validation through the shared schema."""

    def test_schema_validation(self):
        from nomad_tpu.client.driver.fields import FieldSchema, validate_fields

        schema = {"command": FieldSchema("string", required=True),
                  "args": FieldSchema("list"),
                  "count": FieldSchema("int"),
                  "verbose": FieldSchema("bool")}
        assert validate_fields({"command": "/bin/x"}, schema) == []
        assert "missing required field 'command'" in \
            validate_fields({}, schema)[0]
        probs = validate_fields({"command": 5, "args": "no",
                                 "count": "x"}, schema)
        assert len(probs) == 3
        assert validate_fields({"command": "x", "bogus": 1}, schema,
                               strict=True) != []

    def test_driver_validates_config(self):
        from nomad_tpu.client.driver.driver import validate_driver_config
        import pytest as _pytest

        validate_driver_config("exec", {"command": "/bin/true"})
        with _pytest.raises(ValueError):
            validate_driver_config("exec", {})
        with _pytest.raises(ValueError):
            validate_driver_config("exec", {"command": 123})
        with _pytest.raises(ValueError):
            validate_driver_config("qemu", {})
        validate_driver_config("java", {"jar_path": "a.jar"})
        with _pytest.raises(ValueError):
            validate_driver_config("java", {})

    def test_invalid_config_fails_task_cleanly(self, tmp_path):
        """An invalid driver config must surface as a driver failure
        event, not a crash."""
        import time

        from nomad_tpu import mock
        from nomad_tpu.client import Client, ClientConfig
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.structs import structs as s

        srv = Server(ServerConfig(num_schedulers=1))
        srv.start()
        client = None
        try:
            client = Client(ClientConfig(
                alloc_dir=str(tmp_path / "allocs")), rpc=srv)
            client.start()
            deadline = time.time() + 20
            while time.time() < deadline:
                n = srv.node_get(client.node.id)
                if n is not None and n.status == "ready":
                    break
                time.sleep(0.05)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
            for t in tg.tasks:
                t.driver = "mock_driver"
                t.config = {"exit_code": "not-an-int"}  # schema violation
                t.resources.networks = []
                t.services = []
            srv.job_register(job)
            deadline = time.time() + 20
            failed = False
            while time.time() < deadline and not failed:
                for a in srv.job_allocations(job.id):
                    st = (a.task_states or {}).get("web")
                    if st and any("exit_code" in (e.message or "")
                                  and "int" in (e.message or "")
                                  for e in st.events):
                        failed = True
                time.sleep(0.1)
            assert failed, "schema violation never surfaced in task events"
        finally:
            if client is not None:
                client.shutdown()
            srv.shutdown()


class TestCgroupIsolation:
    """executor_linux.go cgroup isolation: exec-family tasks land in a
    per-task cgroup with memory/cpu limits, destroyed with the task."""

    def test_exec_task_runs_in_cgroup(self, tmp_path):
        import subprocess
        import time as _time

        from nomad_tpu.client.driver import cgroups
        from nomad_tpu.client.driver.executor import ExecCommand, Executor

        if not cgroups.available():
            import pytest as _pytest
            _pytest.skip("cgroups not writable on this host")

        cmd = ExecCommand(
            cmd="/bin/sh", args=["-c", "sleep 5"],
            cwd=str(tmp_path), task_name="cg-test",
            memory_limit_mb=64, cpu_limit=100,
            use_cgroups=True, cgroup_name="test-cg-task")
        ex = Executor(cmd)
        pid = ex.launch()
        try:
            assert ex.cgroup is not None and ex.cgroup.paths
            deadline = _time.time() + 5
            while _time.time() < deadline and pid not in ex.cgroup.pids():
                _time.sleep(0.05)
            assert pid in ex.cgroup.pids(), "pid never joined the cgroup"
            mem_path = ex.cgroup.paths[0]
            import os as _os
            if _os.path.exists(_os.path.join(mem_path,
                                             "memory.limit_in_bytes")):
                limit = int(open(_os.path.join(
                    mem_path, "memory.limit_in_bytes")).read())
            else:
                limit = int(open(_os.path.join(mem_path,
                                               "memory.max")).read())
            assert limit == 64 * 1024 * 1024
        finally:
            ex.shutdown(grace=0.2)
            ex.exited.wait(10)
        # group destroyed with the task
        assert ex.cgroup is None

    def test_cgroup_destroy_reaps_stragglers(self, tmp_path):
        import time as _time

        from nomad_tpu.client.driver import cgroups

        if not cgroups.available():
            import pytest as _pytest
            _pytest.skip("cgroups not writable on this host")

        import subprocess
        cg = cgroups.TaskCgroup("straggler-test", memory_mb=32)
        assert cg.create()
        proc = subprocess.Popen(["sleep", "30"])
        cg.add_pid(proc.pid)
        assert proc.pid in cg.pids()
        cg.destroy()
        deadline = _time.time() + 5
        while _time.time() < deadline and proc.poll() is None:
            _time.sleep(0.05)
        assert proc.poll() is not None, "straggler survived cgroup destroy"
        proc.wait()
