"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
(`shard_map` over a Mesh) run without TPU hardware, per the reference test
strategy of simulating multi-node in-process (SURVEY.md §4 item 3).
Must run before jax is imported anywhere.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The environment pre-sets JAX_PLATFORMS to the real TPU tunnel and the
# plugin wins over the env var, so override through the config API (must
# happen before any backend is initialized).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def dev_test_config():
    """AgentConfig.dev() with an ephemeral HTTP port: dev() binds the
    standard 4646 for CLI parity, which concurrent test agents must not
    share."""
    from nomad_tpu.agent import AgentConfig

    cfg = AgentConfig.dev()
    cfg.ports.http = 0
    return cfg
