"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
(`shard_map` over a Mesh) run without TPU hardware, per the reference test
strategy of simulating multi-node in-process (SURVEY.md §4 item 3).
Must run before jax is imported anywhere.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The environment pre-sets JAX_PLATFORMS to the real TPU tunnel and the
# plugin wins over the env var, so override through the config API (must
# happen before any backend is initialized).
os.environ["JAX_PLATFORMS"] = "cpu"
# Columnar differential guard at EVERY encode (ISSUE 9 acceptance: the
# whole suite verifies the column-built buffers bit-identical to the
# object walk; a single mismatch trips the breaker and fails the
# asserting tests).  Respect an explicit override from the environment.
os.environ.setdefault("NOMAD_TPU_COLUMNAR_GUARD_EVERY", "1")
# Struct-codec native/python twin differential guard at EVERY call
# (ISSUE 11): the whole suite bit-compares the C++ string-column pack
# against the pure-Python twin; one mismatch disables native and fails
# the asserting tests.
os.environ.setdefault("NOMAD_TPU_CODEC_GUARD_EVERY", "1")
# Packed-result decode native/twin differential guard at EVERY call
# (ISSUE 13): every COO expand / last-commit-score dedup in the suite is
# bit-compared against the numpy/python twins.
os.environ.setdefault("NOMAD_TPU_DECODE_GUARD_EVERY", "1")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# -- chaos trace dumps --------------------------------------------------------
# Chaos scenarios (`@pytest.mark.chaos`) run with the eval-lifecycle
# tracing plane armed; when one fails — the probabilistic sweeps fail
# rarely and only under particular seeds — the recent span timeline is
# dumped (bounded) to stderr so the failure is diagnosable from the
# pytest log alone, without re-running the seed locally.

CHAOS_DUMP_SPANS = 120
CHAOS_DUMP_EVENTS = 80


@pytest.fixture(autouse=True)
def _chaos_tracing(request):
    if request.node.get_closest_marker("chaos") is None:
        yield
        return
    from nomad_tpu.server import event_broker
    from nomad_tpu.utils import knobs, lockcheck, tracing

    tracing.enable()
    # Arm the cluster event stream for every server the test constructs
    # (NOMAD_TPU_EVENTS is read at Server construction) and clear the
    # process-global forensic tail so a failure dump shows THIS test's
    # incident, not the previous one's.
    prev = os.environ.get("NOMAD_TPU_EVENTS")
    os.environ["NOMAD_TPU_EVENTS"] = "1"
    event_broker.clear_recent()
    # Runtime lock-order sanitizer (ISSUE 15): chaos tests construct
    # full servers under induced concurrency — every lock they create
    # is instrumented, and teardown asserts the accumulated acquisition
    # graph has no cycle (the witness chain prints on failure).  The
    # env knob lets a run opt out (NOMAD_TPU_LOCKCHECK=0/false/no/off,
    # the registry's falsy set); an operator arming the whole session
    # (NOMAD_TPU_LOCKCHECK=1) keeps the sanitizer armed and the env var
    # intact after teardown.
    prev_lockcheck = os.environ.get("NOMAD_TPU_LOCKCHECK")
    lock_sanitize = knobs.get_bool("NOMAD_TPU_LOCKCHECK", True)
    was_armed = lockcheck.armed()
    if lock_sanitize:
        lockcheck.arm()
        os.environ["NOMAD_TPU_LOCKCHECK"] = "1"
    try:
        yield
        if lock_sanitize:
            lockcheck.assert_acyclic()
    finally:
        if lock_sanitize and not was_armed:
            lockcheck.disarm()
        if prev_lockcheck is None:
            os.environ.pop("NOMAD_TPU_LOCKCHECK", None)
        else:
            os.environ["NOMAD_TPU_LOCKCHECK"] = prev_lockcheck
        if prev is None:
            os.environ.pop("NOMAD_TPU_EVENTS", None)
        else:
            os.environ["NOMAD_TPU_EVENTS"] = prev
        tracing.disable()


def _format_trace(spans):
    t0 = min(sp["Start"] for sp in spans)
    lines = []
    for sp in spans:
        lines.append(
            "  +{:10.2f}ms {:9.2f}ms  {:<26} {}".format(
                (sp["Start"] - t0) * 1000.0, sp["DurationMs"],
                sp["Name"], sp["Attrs"]))
    return "\n".join(lines)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    # After the call phase, before fixture teardown disarms the tracer.
    if (rep.when == "call" and rep.failed
            and item.get_closest_marker("chaos") is not None):
        from nomad_tpu.server import event_broker
        from nomad_tpu.utils import tracing

        spans = tracing.recent(CHAOS_DUMP_SPANS)
        print(f"\n-- chaos trace timeline for {item.nodeid} "
              f"(last {len(spans)} spans) --", file=sys.__stderr__)
        if spans:
            print(_format_trace(spans), file=sys.__stderr__)
        else:
            print("  (no spans recorded)", file=sys.__stderr__)
        # The cluster event timeline next to the trace: spans say where
        # time went, events say what the cluster state DID.
        events = event_broker.recent(CHAOS_DUMP_EVENTS)
        print(f"-- chaos event timeline for {item.nodeid} "
              f"(last {len(events)} events) --", file=sys.__stderr__)
        if events:
            for ev in events:
                extra = f" eval={ev.eval_id[:8]}" if ev.eval_id else ""
                print(f"  @{ev.index:<6} {ev.topic}/{ev.type:<22} "
                      f"{ev.key[:16]}{extra} {ev.payload}",
                      file=sys.__stderr__)
        else:
            print("  (no events recorded)", file=sys.__stderr__)


def dev_test_config():
    """AgentConfig.dev() with an ephemeral HTTP port: dev() binds the
    standard 4646 for CLI parity, which concurrent test agents must not
    share."""
    from nomad_tpu.agent import AgentConfig

    cfg = AgentConfig.dev()
    cfg.ports.http = 0
    return cfg
