"""Consul-equivalent tests: service catalog, task service lifecycle,
checks, agent self-registration, and client server-discovery
(reference: command/agent/consul/client.go:87, client/client.go:2139,
command/agent/agent.go:492)."""
import time

import pytest

import conftest

from nomad_tpu import mock
from nomad_tpu.consul import CatalogEntry, ServiceCatalog, ServiceClient
from nomad_tpu.consul.catalog import CHECK_CRITICAL, CHECK_PASSING
from nomad_tpu.structs import structs as s

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


def wait_until(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


class TestCatalog:
    def test_register_query_deregister(self):
        cat = ServiceCatalog()
        cat.register(CatalogEntry(id="a", name="web", tags=["v1"],
                                  address="10.0.0.1", port=80))
        cat.register(CatalogEntry(id="b", name="web", tags=["v2"],
                                  address="10.0.0.2", port=81))
        cat.register(CatalogEntry(id="c", name="db", address="10.0.0.3",
                                  port=5432))
        assert set(cat.services()) == {"web", "db"}
        assert sorted(cat.services()["web"]) == ["v1", "v2"]
        assert [e.address for e in cat.service("web")] == \
            ["10.0.0.1", "10.0.0.2"]
        assert [e.id for e in cat.service("web", tag="v2")] == ["b"]
        cat.deregister("a")
        assert [e.id for e in cat.service("web")] == ["b"]


class TestServiceClient:
    def make_alloc_with_service(self, checks=()):
        job = mock.job()
        task = job.task_groups[0].tasks[0]
        task.services = [s.Service(
            name="web-frontend", port_label="http", tags=["prod"],
            checks=list(checks))]
        alloc = mock.alloc()
        alloc.job = job
        alloc.task_resources = {"web": s.Resources(networks=[
            s.NetworkResource(device="eth0", ip="192.168.1.10", mbits=10,
                              dynamic_ports=[s.Port("http", 23456)])])}
        return alloc, task

    def test_task_service_lifecycle(self):
        cat = ServiceCatalog()
        sc = ServiceClient(cat)
        alloc, task = self.make_alloc_with_service()
        sc.register_task(alloc, task)
        entries = cat.service("web-frontend")
        assert len(entries) == 1
        e = entries[0]
        assert e.address == "192.168.1.10" and e.port == 23456
        assert e.tags == ["prod"]
        assert alloc.id in e.id and "web" in e.id
        sc.deregister_task(alloc.id, task.name)
        assert cat.service("web-frontend") == []

    def test_script_check_runs_through_exec(self):
        cat = ServiceCatalog()
        sc = ServiceClient(cat)
        sc.start()
        try:
            chk = s.ServiceCheck(name="status", type="script",
                                 command="/bin/check", interval=0.1)
            alloc, task = self.make_alloc_with_service(checks=[chk])
            calls = {"n": 0}

            def exec_fn(cmd, args):
                # DriverHandle.exec_cmd shape: (output, exit_code)
                calls["n"] += 1
                return f"run {calls['n']}", (0 if calls["n"] < 3 else 1)

            sc.register_task(alloc, task, exec_fn=exec_fn)
            entries = cat.service("web-frontend")
            cid = entries[0].checks[0].id
            sid = entries[0].id
            assert wait_until(lambda: calls["n"] >= 3, 5.0)
            assert wait_until(lambda: cat.entry(sid).checks[0].status ==
                              CHECK_CRITICAL, 5.0)
            assert not cat.entry(sid).healthy()
        finally:
            sc.stop()

    def test_tcp_check(self):
        import socketserver
        import threading

        class Quiet(socketserver.BaseRequestHandler):
            def handle(self):
                pass

        srv = socketserver.TCPServer(("127.0.0.1", 0), Quiet)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            cat = ServiceCatalog()
            sc = ServiceClient(cat)
            sc.start()
            chk = s.ServiceCheck(name="up", type="tcp", port_label="http",
                                 interval=0.1, timeout=1.0)
            alloc, task = self.make_alloc_with_service(checks=[chk])
            alloc.task_resources["web"].networks[0].ip = "127.0.0.1"
            alloc.task_resources["web"].networks[0].dynamic_ports = [
                s.Port("http", port)]
            sc.register_task(alloc, task)
            sid = cat.service("web-frontend")[0].id
            assert wait_until(
                lambda: cat.entry(sid).checks[0].output == "tcp connect ok",
                5.0)
            sc.stop()
        finally:
            srv.shutdown()
            srv.server_close()


class TestAgentIntegration:
    """Services ride the task lifecycle; agents self-register; clients
    discover servers through the catalog HTTP surface."""

    def _wait_ready(self, srv, client):
        return wait_until(lambda: srv.node_get(client.node.id) is not None
                          and srv.node_get(client.node.id).status == "ready")

    def test_services_follow_alloc_lifecycle(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig

        cfg = conftest.dev_test_config()
        cfg.client.state_dir = str(tmp_path / "state")
        cfg.client.alloc_dir = str(tmp_path / "allocs")
        agent = Agent(cfg)
        agent.start()
        try:
            assert self._wait_ready(agent.server, agent.client)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.restart_policy = s.RestartPolicy(attempts=0, mode="fail")
            for t in tg.tasks:
                t.driver = "mock_driver"
                t.config = {"run_for": "60s"}
                t.resources.networks = []
                t.services = [s.Service(name="web-svc", tags=["t1"])]
            agent.server.job_register(job)
            assert wait_until(lambda: len(
                agent.catalog.service("web-svc")) == 1, 20.0), \
                "service not registered with running task"

            agent.server.job_deregister(job.id, purge=False)
            assert wait_until(lambda: agent.catalog.service("web-svc") == [],
                              20.0), "service not deregistered on stop"
        finally:
            agent.shutdown()

    def test_agent_self_registration_and_discovery(self, tmp_path):
        from nomad_tpu.agent.agent import Agent
        from nomad_tpu.agent.config import AgentConfig

        # Server-only agent hosting the catalog.
        scfg = AgentConfig()
        scfg.name = "srv"
        scfg.data_dir = str(tmp_path / "srv")
        scfg.server.enabled = True
        scfg.server.data_dir = str(tmp_path / "srv")
        scfg.ports.http = 0
        scfg.ports.rpc = 0
        server_agent = Agent(scfg)
        server_agent.start()
        client_agent = None
        try:
            nomads = server_agent.catalog.service("nomad")
            assert len(nomads) == 1
            rpc_addr = server_agent.server.config.rpc_advertise
            assert f"{nomads[0].address}:{nomads[0].port}" == rpc_addr

            # Client-only agent with NO server list — discovers via the
            # catalog HTTP surface (client.go:2139 consulDiscovery).
            ccfg = AgentConfig()
            ccfg.name = "cli"
            ccfg.client.enabled = True
            ccfg.client.state_dir = str(tmp_path / "cstate")
            ccfg.client.alloc_dir = str(tmp_path / "callocs")
            ccfg.client.servers = ["127.0.0.1:1"]  # dead on purpose
            ccfg.client.consul_address = server_agent.http.address
            ccfg.ports.http = 0
            client_agent = Agent(ccfg)
            # fast retry so the test doesn't sit through the 15s interval
            import nomad_tpu.client.client as cmod
            orig = cmod.REGISTER_RETRY_INTERVAL
            cmod.REGISTER_RETRY_INTERVAL = 0.3
            try:
                client_agent.start()
                assert self._wait_ready(server_agent.server,
                                        client_agent.client), \
                    "client never registered via discovered servers"
            finally:
                cmod.REGISTER_RETRY_INTERVAL = orig
        finally:
            if client_agent is not None:
                client_agent.shutdown()
            server_agent.shutdown()
