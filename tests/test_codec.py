"""Struct codec (ISSUE 11): randomized round-trip parity against the
reflection-msgpack path, frame rejection semantics, per-connection
codec negotiation (old peers negotiate down), the NOMAD_TPU_CODEC=0
kill switch in both directions, and the native/python twin guard.
"""
from __future__ import annotations

import os
import random
import socket
import subprocess
import sys
import time

import msgpack
import pytest

from nomad_tpu import codec, mock
from nomad_tpu.api.codec import ensure, from_wire, to_wire
from nomad_tpu.codec import CodecError
from nomad_tpu.codec import native as codec_native
from nomad_tpu.server.log_codec import decode_payload, encode_payload
from nomad_tpu.server.rpc import (
    RPC_NOMAD,
    ConnPool,
    RPCServer,
    TransportError,
    _Conn,
    _recv_frame,
)
from nomad_tpu.structs import structs as s

pytestmark = pytest.mark.codec


# ---------------------------------------------------------------------------
# random instance builders (None/empty-collection edges included)
# ---------------------------------------------------------------------------


def _rstr(rng, allow_empty=True):
    choices = ["", "x", "web-frontend", "dc-1", "uniçode-ü",
               "a" * 200, s.generate_uuid()]
    v = rng.choice(choices if allow_empty else choices[1:])
    return v


def _rint(rng):
    return rng.choice([0, 1, -1, 127, 128, -12345, 2**40, -(2**40)])


def _rfloat(rng):
    return rng.choice([0.0, 1.5, -2.25, 1e-9, 3600.0, 1234567.875])


def rand_resources(rng, nets=True):
    r = s.Resources(cpu=_rint(rng), memory_mb=abs(_rint(rng)),
                    disk_mb=abs(_rint(rng)), iops=_rint(rng))
    if nets and rng.random() < 0.5:
        r.networks = [s.NetworkResource(
            device=_rstr(rng), cidr="10.0.0.0/8", ip="10.0.0.1",
            mbits=_rint(rng),
            reserved_ports=[s.Port(_rstr(rng), rng.randrange(1 << 16))
                            for _ in range(rng.randrange(3))],
            dynamic_ports=[s.Port("http", 0)] * rng.randrange(2))]
    return r


def rand_node(rng):
    return s.Node(
        id=s.generate_uuid(), datacenter=_rstr(rng), name=_rstr(rng),
        http_addr="127.0.0.1:4646",
        attributes={_rstr(rng, False): _rstr(rng)
                    for _ in range(rng.randrange(4))},
        resources=rand_resources(rng),
        reserved=rand_resources(rng) if rng.random() < 0.5 else None,
        links={}, meta={"rack": "r1"} if rng.random() < 0.5 else {},
        node_class=_rstr(rng), drain=rng.random() < 0.2,
        status=rng.choice([s.NODE_STATUS_INIT, s.NODE_STATUS_READY]),
        status_updated_at=_rfloat(rng),
        create_index=abs(_rint(rng)), modify_index=abs(_rint(rng)))


def rand_job(rng):
    job = mock.job()
    job.priority = rng.randrange(1, 100)
    job.payload = rng.choice([b"", b"\x00\xff binary \xc1"])
    job.meta = {} if rng.random() < 0.5 else {"k": _rstr(rng)}
    job.periodic = (None if rng.random() < 0.7 else
                    s.PeriodicConfig(enabled=True, spec="*/5 * * * *"))
    if rng.random() < 0.3:
        job.task_groups = []
    for tg in job.task_groups:
        tg.constraints = ([] if rng.random() < 0.5 else
                          [s.Constraint("${attr.kernel.name}", "linux",
                                        "=")])
        for t in tg.tasks:
            t.config = rng.choice([
                {}, {"command": "/bin/date", "args": ["-u"]},
                {"nested": {"deep": [1, 2.5, None, True, "s"]}}])
            t.env = {} if rng.random() < 0.5 else {"PORT": "80"}
    return job


def rand_alloc(rng, with_job=True):
    a = s.Allocation(
        id=s.generate_uuid(), eval_id=s.generate_uuid(),
        name=_rstr(rng), node_id=s.generate_uuid(),
        job_id=_rstr(rng, False),
        job=rand_job(rng) if with_job and rng.random() < 0.5 else None,
        task_group="tg",
        resources=rand_resources(rng) if rng.random() < 0.5 else None,
        shared_resources=(rand_resources(rng, nets=False)
                          if rng.random() < 0.3 else None),
        task_resources={_rstr(rng, False): rand_resources(rng)
                        for _ in range(rng.randrange(3))},
        metrics=(None if rng.random() < 0.5 else s.AllocMetric(
            nodes_evaluated=_rint(rng),
            scores={f"{s.generate_uuid()}.binpack": _rfloat(rng)},
            class_filtered={}, dimension_exhausted={"cpu": 1})),
        desired_status=s.ALLOC_DESIRED_STATUS_RUN,
        client_status=rng.choice([s.ALLOC_CLIENT_STATUS_PENDING,
                                  s.ALLOC_CLIENT_STATUS_RUNNING]),
        task_states={"t": s.TaskState(events=[
            s.TaskEvent(type=s.TASK_STARTED, time=_rfloat(rng))])}
        if rng.random() < 0.4 else {},
        previous_allocation=("" if rng.random() < 0.7
                             else s.generate_uuid()),
        create_index=abs(_rint(rng)), modify_index=abs(_rint(rng)),
        create_time=_rfloat(rng))
    return a


def rand_eval(rng):
    return s.Evaluation(
        id=s.generate_uuid(), priority=rng.randrange(1, 100),
        type=s.JOB_TYPE_SERVICE, triggered_by=s.EVAL_TRIGGER_JOB_REGISTER,
        job_id=_rstr(rng, False), job_modify_index=abs(_rint(rng)),
        node_id="" if rng.random() < 0.5 else s.generate_uuid(),
        status=s.EVAL_STATUS_PENDING, wait=_rfloat(rng),
        failed_tg_allocs={} if rng.random() < 0.6 else {
            "tg": s.AllocMetric(nodes_exhausted=3,
                                constraint_filtered={"c": 1})},
        class_eligibility={} if rng.random() < 0.5 else
        {"class-a": True, "class-b": False},
        escaped_computed_class=rng.random() < 0.5,
        queued_allocations={} if rng.random() < 0.5 else {"tg": 4},
        snapshot_index=abs(_rint(rng)))


def rand_slab(rng, lazy=True):
    n = rng.randrange(1, 12)
    proto = rand_alloc(rng, with_job=False)
    proto.id = proto.name = proto.node_id = ""
    if lazy and rng.random() < 0.5:
        ids, names = s.LazyUuids(n), s.LazyNames(n, "job.tg")
    else:
        ids = [s.generate_uuid() for _ in range(n)]
        names = [f"job.tg[{i}]" for i in range(n)]
    return s.AllocSlab(
        proto=proto, ids=ids, names=names,
        node_ids=[s.generate_uuid() for _ in range(n)],
        prev_ids=[] if rng.random() < 0.5 else [""] * n,
        create_index=abs(_rint(rng)), modify_index=abs(_rint(rng)))


def rand_plan(rng):
    p = s.Plan(
        eval_id=s.generate_uuid(), eval_token=s.generate_uuid(),
        snapshot_index=abs(_rint(rng)), priority=rng.randrange(100),
        all_at_once=rng.random() < 0.5, job=rand_job(rng))
    for _ in range(rng.randrange(3)):
        p.append_alloc(rand_alloc(rng, with_job=False))
    if rng.random() < 0.4:
        p.alloc_slabs.append(rand_slab(rng))
    if rng.random() < 0.3:
        victim = rand_alloc(rng, with_job=False)
        p.append_preempted_alloc(victim)
    return p


def rand_plan_result(rng):
    return s.PlanResult(
        node_update={}, node_allocation={
            s.generate_uuid(): [rand_alloc(rng, with_job=False)]},
        alloc_slabs=[rand_slab(rng)] if rng.random() < 0.5 else [],
        node_preemptions={}, refresh_index=abs(_rint(rng)),
        alloc_index=abs(_rint(rng)))


BUILDERS = [rand_node, rand_job, rand_alloc, rand_eval, rand_slab,
            rand_plan, rand_plan_result]


def _materialize(x):
    """to_wire comparison basis: lazy columns and dataclass trees both
    normalize to their wire-dict form."""
    return to_wire(x)


def msgpack_path(obj):
    """The reflection-msgpack round trip the codec must be bit-equal
    to: to_wire -> msgpack -> from_wire."""
    wire = msgpack.unpackb(
        msgpack.packb(to_wire(obj), use_bin_type=True), raw=False)
    return from_wire(type(obj), wire)


# ---------------------------------------------------------------------------
# round-trip parity
# ---------------------------------------------------------------------------


class TestRoundTrip:
    def test_every_hot_type_matches_msgpack_path(self):
        rng = random.Random(11)
        for builder in BUILDERS:
            for _ in range(10):
                obj = builder(rng)
                got = codec.decode(codec.encode(obj))
                assert type(got) is type(obj)
                assert _materialize(got) == _materialize(msgpack_path(obj)), \
                    f"{builder.__name__} diverged from the msgpack path"

    def test_none_and_empty_collection_edges(self):
        ev = s.Evaluation()  # all defaults: empty dicts, zero ints
        assert _materialize(codec.decode(codec.encode(ev))) \
            == _materialize(ev)
        a = s.Allocation()  # every Optional None
        assert _materialize(codec.decode(codec.encode(a))) \
            == _materialize(a)
        p = s.Plan()  # job=None, empty maps
        got = codec.decode(codec.encode(p))
        assert got.job is None and got.node_allocation == {}
        slab = s.AllocSlab()  # proto=None, empty columns
        got = codec.decode(codec.encode(slab))
        assert got.proto is None and list(got.ids) == []

    def test_lazy_slab_columns_survive_compact(self):
        slab = s.AllocSlab(proto=s.Allocation(job_id="j"),
                           ids=s.LazyUuids(100000),
                           names=s.LazyNames(100000, "j.tg"),
                           node_ids=["n1"] * 4, prev_ids=[])
        blob = codec.encode(slab)
        # The formulaic columns must ride as generator specs, not 100k
        # materialized strings (the PR 9/10 log/wire compaction).
        assert len(blob) < 1000
        got = codec.decode(blob)
        assert type(got.ids) is s.LazyUuids and got.ids.n == 100000
        assert got.ids[7] == slab.ids[7]
        assert got.names[99999] == slab.names[99999]

    def test_envelopes_round_trip(self):
        rng = random.Random(5)
        dq_reply = {"Evals": [{"Eval": rand_eval(rng), "Token": "tok",
                               "Attempts": 1, "PlanFence": 7}],
                    "AppliedIndex": 42}
        got = codec.decode(codec.encode(dq_reply))
        assert isinstance(got["Evals"][0]["Eval"], s.Evaluation)
        assert got["AppliedIndex"] == 42
        submit = {"Plan": rand_plan(rng), "__forwarded__": True}
        got = codec.decode(codec.encode(submit))
        assert isinstance(got["Plan"], s.Plan)
        hb = {"NodeID": "n1", "Status": "ready"}
        assert codec.decode(codec.encode(hb)) == hb

    @pytest.mark.slow
    def test_fuzz_sweep(self):
        for seed in range(24):
            rng = random.Random(seed)
            for builder in BUILDERS:
                for _ in range(25):
                    obj = builder(rng)
                    got = codec.decode(codec.encode(obj))
                    assert _materialize(got) \
                        == _materialize(msgpack_path(obj))


# ---------------------------------------------------------------------------
# rejection semantics
# ---------------------------------------------------------------------------


class TestFrameRejection:
    def test_every_truncation_rejected(self):
        rng = random.Random(3)
        blob = codec.encode({"plan": rand_plan(rng),
                             "evals": [rand_eval(rng)]})
        for k in range(len(blob)):
            with pytest.raises(CodecError):
                codec.decode(blob[:k])

    def test_trailing_garbage_rejected(self):
        blob = codec.encode({"a": 1})
        with pytest.raises(CodecError, match="trailing"):
            codec.decode(blob + b"\x00")

    def test_bad_magic_version_fingerprint_and_type_id(self):
        header = bytes([codec.MAGIC, codec.VERSION]) + codec.FINGERPRINT
        with pytest.raises(CodecError, match="magic"):
            codec.decode(b"\x00\x01\x00")
        with pytest.raises(CodecError, match="version"):
            codec.decode(bytes([codec.MAGIC, 99]) + codec.FINGERPRINT
                         + b"\x00")
        # A frame from a peer on a DIFFERENT struct schema: positional
        # type ids would shift, so the fingerprint gate must reject it
        # before any layout is trusted (rolling-upgrade safety for
        # raft/WAL/snapshot frames that never cross a handshake).
        drifted = bytes([codec.MAGIC, codec.VERSION]) \
            + bytes(8) + b"\x00"
        with pytest.raises(CodecError, match="fingerprint"):
            codec.decode(drifted)
        # struct tag with an out-of-registry type id
        w = bytearray(header) + bytes([9, 0xFF, 0x7F])
        with pytest.raises(CodecError, match="type id"):
            codec.decode(bytes(w))

    def test_int_out_of_64bit_range_fails_at_encode(self):
        """An unbounded int must fail at ENCODE (falling back to the
        msgpack path, which raises its own OverflowError) — never
        produce a frame the decoder's varint cap rejects after it was
        persisted/replicated."""
        with pytest.raises(CodecError, match="64-bit"):
            from nomad_tpu.codec.gen import encode_frame

            encode_frame({"i": 1 << 90})
        # int64 edges still round-trip
        edge = {"a": (1 << 63) - 1, "b": -(1 << 63)}
        assert codec.decode(codec.encode(edge)) == edge

    def test_oversized_counts_rejected_without_allocation(self):
        # list claiming 2^40 elements in a tiny frame
        w = bytearray([codec.MAGIC, codec.VERSION]) + codec.FINGERPRINT
        w.append(7)
        n = 1 << 40
        while n > 0x7F:
            w.append(0x80 | (n & 0x7F))
            n >>= 7
        w.append(n)
        with pytest.raises(CodecError):
            codec.decode(bytes(w))

    def test_bad_codec_frame_on_wire_is_transport_error(self):
        """A torn codec frame must surface exactly like _recv_frame's
        msgpack TransportError semantics (ISSUE 11 satellite)."""
        a, b = socket.socketpair()
        try:
            blob = codec.encode({"x": 1})
            torn = blob[: len(blob) - 1]
            a.sendall(len(torn).to_bytes(4, "little") + torn)
            with pytest.raises(TransportError, match="codec frame"):
                _recv_frame(b)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# log / snapshot integration + kill switch (both directions)
# ---------------------------------------------------------------------------


class TestLogCodecAndKillSwitch:
    def test_log_payload_codec_frames(self):
        rng = random.Random(9)
        payload = {"allocs": [rand_alloc(rng)], "slabs": [rand_slab(rng)],
                   "job": rand_job(rng), "eval_id": "e1"}
        blob = encode_payload(payload)
        assert codec.is_frame(blob)
        got = decode_payload(blob)
        assert _materialize(got["job"]) \
            == _materialize(msgpack_path(payload["job"]))
        assert isinstance(got["allocs"][0], s.Allocation)

    def test_kill_switch_both_directions(self, monkeypatch):
        rng = random.Random(13)
        payload = {"node": rand_node(rng)}
        codec_blob = encode_payload(payload)
        assert codec.is_frame(codec_blob)
        monkeypatch.setenv("NOMAD_TPU_CODEC", "0")
        codec.reset()
        try:
            # Disabled: writes the legacy tagged-msgpack tree…
            legacy_blob = encode_payload(payload)
            assert not codec.is_frame(legacy_blob)
            # …but still DECODES codec frames already on disk/wire.
            got = decode_payload(codec_blob)
            assert isinstance(got["node"], s.Node)
        finally:
            monkeypatch.delenv("NOMAD_TPU_CODEC")
            codec.reset()
        # Re-enabled: legacy blobs written while disabled still decode.
        got = decode_payload(legacy_blob)
        assert isinstance(got["node"], s.Node)
        assert _materialize(got["node"]) == _materialize(
            decode_payload(codec_blob)["node"])

    def test_filelog_mixed_format_recovery(self, tmp_path, monkeypatch):
        """Entries appended under either switch position replay
        together after restart (one WAL, mixed frames)."""
        from nomad_tpu.server.fsm import FSM, MessageType
        from nomad_tpu.server.raft import FileLog

        node = mock.node()
        node.compute_class()
        job = mock.job()
        flog = FileLog(FSM(), str(tmp_path))
        flog.apply(MessageType.NODE_REGISTER, {"node": node})
        flog.close()
        monkeypatch.setenv("NOMAD_TPU_CODEC", "0")
        codec.reset()
        try:
            flog2 = FileLog(FSM(), str(tmp_path))
            assert flog2.fsm.state.node_by_id(None, node.id) is not None
            flog2.apply(MessageType.JOB_REGISTER, {"job": job})
            flog2.close()
        finally:
            monkeypatch.delenv("NOMAD_TPU_CODEC")
            codec.reset()
        flog3 = FileLog(FSM(), str(tmp_path))
        assert flog3.fsm.state.node_by_id(None, node.id) is not None
        assert flog3.fsm.state.job_by_id(None, job.id) is not None
        flog3.close()

    def test_snapshot_sections_ride_codec(self, monkeypatch):
        from nomad_tpu.state.state_store import StateStore

        store = StateStore()
        node = mock.node()
        node.compute_class()
        store.upsert_node(1, node)
        store.upsert_job(2, mock.job())
        blob = store.persist()
        restored = StateStore.restore(blob)
        assert restored.node_by_id(None, node.id) is not None
        # Kill switch: the snapshot written with codec frames must still
        # restore with the switch off (decode is sniff-based).
        monkeypatch.setenv("NOMAD_TPU_CODEC", "0")
        codec.reset()
        try:
            restored2 = StateStore.restore(blob)
            assert restored2.node_by_id(None, node.id) is not None
            legacy = restored2.persist()
        finally:
            monkeypatch.delenv("NOMAD_TPU_CODEC")
            codec.reset()
        assert StateStore.restore(legacy).node_by_id(None, node.id) \
            is not None


# ---------------------------------------------------------------------------
# per-connection negotiation
# ---------------------------------------------------------------------------


def _typed_echo_server():
    srv = RPCServer()
    srv.register("Echo", lambda body: body)
    srv.register("GetEval", lambda body: {"Eval": s.Evaluation(
        id="e-1", job_id="j-1", wait=1.5)})
    srv.start()
    return srv


class TestNegotiation:
    def test_codec_peers_speak_typed_frames(self):
        srv = _typed_echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            reply = pool.call(srv.address, "GetEval", {})
            ev = reply["Eval"]
            assert isinstance(ev, s.Evaluation) and ev.id == "e-1"
            assert ev.wait == 1.5
            # ensure() passes typed values through untouched
            assert ensure(s.Evaluation, ev) is ev
            assert srv.address not in pool._legacy_addrs
        finally:
            pool.close()
            srv.shutdown()

    def test_old_client_against_new_server(self):
        """A legacy dialer (0x01 channel, msgpack frames, wire dicts)
        gets exactly the old CamelCase surface from a codec server."""
        srv = _typed_echo_server()
        conn = _Conn(srv.address, RPC_NOMAD, 5.0)
        try:
            assert not conn.binary
            reply = conn.call("GetEval", {}, 5.0)
            assert reply["Eval"]["ID"] == "e-1"  # wire dict, not typed
            assert reply["Eval"]["Wait"] == 1.5
            echoed = conn.call("Echo", {"A": [1, "x"]}, 5.0)
            assert echoed == {"A": [1, "x"]}
        finally:
            conn.close()
            srv.shutdown()

    def test_old_server_negotiates_down_per_connection(self):
        """Dialing an old (codec-less) peer: the codec handshake is
        refused, the pool remembers the address and redials legacy —
        calls succeed transparently (ISSUE 11 mixed-codec satellite)."""
        child = subprocess.Popen(
            [sys.executable, "-c", (
                "import os, sys\n"
                "os.environ['NOMAD_TPU_CODEC'] = '0'\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                "from nomad_tpu.server.rpc import RPCServer\n"
                "srv = RPCServer()\n"
                "srv.register('Echo', lambda body: body)\n"
                "srv.start()\n"
                "print('READY', srv.address, flush=True)\n"
                "sys.stdin.read()\n")],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, NOMAD_TPU_CODEC="0",
                     JAX_PLATFORMS="cpu"))
        try:
            line = child.stdout.readline()
            assert line.startswith("READY "), line
            addr = line.split()[1]
            pool = ConnPool(timeout=5.0)
            try:
                assert pool.call(addr, "Echo", {"X": 1}) == {"X": 1}
                assert addr in pool._legacy_addrs
                # Second call: no re-probe, still legacy, still works.
                assert pool.call(addr, "Echo", {"Y": 2}) == {"Y": 2}
            finally:
                pool.close()
        finally:
            child.stdin.close()
            child.wait(timeout=10)

    def test_handshake_timeout_does_not_pin_legacy(self):
        """A stalled/restarting codec peer is a TRANSIENT failure: the
        dial errors, but the address must NOT be demoted to msgpack for
        the process lifetime (only an orderly refusal — the old-build
        signature — pins legacy)."""
        import threading

        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        addr = f"127.0.0.1:{lst.getsockname()[1]}"
        stop = threading.Event()

        def stall():
            conn, _ = lst.accept()
            stop.wait(5.0)  # read nothing, send nothing, hold open
            conn.close()

        t = threading.Thread(target=stall, daemon=True)
        t.start()
        pool = ConnPool(timeout=0.3)
        try:
            with pytest.raises(Exception):
                pool.call(addr, "Echo", {})
            assert addr not in pool._legacy_addrs
        finally:
            stop.set()
            pool.close()
            lst.close()
            t.join(timeout=2)

    def test_kill_switch_restores_msgpack_everywhere(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_CODEC", "0")
        codec.reset()
        srv = _typed_echo_server()
        pool = ConnPool(timeout=5.0)
        try:
            reply = pool.call(srv.address, "GetEval", {})
            # Pure msgpack end to end: wire dict surface.
            assert reply["Eval"]["ID"] == "e-1"
            assert ensure(s.Evaluation, reply["Eval"]).id == "e-1"
        finally:
            pool.close()
            srv.shutdown()
            monkeypatch.delenv("NOMAD_TPU_CODEC")
            codec.reset()


# ---------------------------------------------------------------------------
# mixed-codec cluster (old msgpack-only peer joins a new-codec cluster)
# ---------------------------------------------------------------------------


class TestMixedCodecCluster:
    def test_legacy_follower_schedules_against_codec_leader(self):
        """A real subprocess follower running with NOMAD_TPU_CODEC=0
        (an 'old build') joins a codec-enabled leader, replicates the
        FSM, follower-read schedules, and forwards plans — every
        leader<->follower frame negotiated down per connection."""
        from nomad_tpu.server import Server, ServerConfig

        cfg = ServerConfig(node_name="codec-leader", enable_rpc=True,
                           bootstrap_expect=1, num_schedulers=0,
                           min_heartbeat_ttl=60.0)
        cfg.force_multi_raft = True
        leader = Server(cfg)
        leader.start()
        child = None
        try:
            deadline = time.monotonic() + 10
            while not leader.is_leader() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert leader.is_leader()
            child = subprocess.Popen(
                [sys.executable, "-m", "nomad_tpu.loadgen",
                 "--follower-child", "--join",
                 leader.config.rpc_advertise, "--workers", "1",
                 "--name", "legacy-follower"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
                env=dict(os.environ, NOMAD_TPU_CODEC="0",
                         JAX_PLATFORMS="cpu"))
            line = ""
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                line = child.stdout.readline()
                if line:
                    break
            assert line.startswith("READY "), line
            follower_addr = line.split()[1]

            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.status = s.NODE_STATUS_READY
            leader.node_register(node)
            job = mock.job()
            for tg in job.task_groups:
                tg.count = 2
                for t in tg.tasks:
                    t.resources.networks = []
            _, eval_id = leader.job_register(job)

            def eval_complete():
                ev = leader.state.eval_by_id(None, eval_id)
                return ev is not None \
                    and ev.status == s.EVAL_STATUS_COMPLETE
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not eval_complete():
                time.sleep(0.05)
            assert eval_complete(), "legacy follower never scheduled"
            assert len(leader.state.allocs_by_job(None, job.id)) == 2

            # The placements replicate BACK to the legacy follower and
            # are readable over its (msgpack-only) wire.
            got = leader.pool.call(follower_addr, "Job.Get",
                                   {"JobID": job.id}, timeout=10.0)
            assert got["Job"] is not None
            assert ensure(s.Job, got["Job"]).id == job.id
            assert follower_addr in leader.pool._legacy_addrs
        finally:
            if child is not None:
                child.stdin.close()
                try:
                    child.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    child.kill()
            leader.shutdown()


# ---------------------------------------------------------------------------
# native twin
# ---------------------------------------------------------------------------


class TestNativeTwin:
    def test_twins_bit_identical_on_corpus(self):
        rng = random.Random(21)
        for _ in range(20):
            strs = [_rstr(rng) for _ in range(rng.randrange(0, 50))] \
                + [s.generate_uuid() for _ in range(rng.randrange(50))]
            encoded = [x.encode("utf-8") for x in strs]
            py = codec_native._py_pack_strs(encoded)
            assert codec_native.pack_strs(strs) == py
            got, end = codec_native.unpack_strs(py, 0, len(strs))
            assert got == strs and end == len(py)
            twin, twin_end = codec_native._py_split_strs(py, 0, len(strs))
            assert twin == strs and twin_end == end

    def test_split_rejects_truncation(self):
        strs = ["abc", "def" * 100]
        blob = codec_native._py_pack_strs(
            [x.encode() for x in strs])
        for k in range(len(blob)):
            with pytest.raises(CodecError):
                codec_native.unpack_strs(blob[:k], 0, len(strs))

    def test_guard_counts_runs(self):
        if codec_native._get_lib() is None:
            pytest.skip("native codec unavailable")
        before = codec_native.GUARD_RUNS
        codec_native.pack_strs(["a", "bb", "ccc"])  # guard_every=1 (conftest)
        assert codec_native.GUARD_RUNS > before
        assert codec_native.GUARD_MISMATCHES == 0
