"""Concurrent multi-worker drain: stale-snapshot workers + plan-apply
conflict handling converge to the same final cluster state as the serial
path (ISSUE 7 tentpole (a)).

The oracle-parity discipline here is outcome-level: node CHOICE is
randomized (power-of-two-choices sampling), so "identical final
placements" means the invariants that define a correct drain —
every job fully placed exactly once (no lost evals, no double
placements), zero overcommit on every node, every eval terminal —
hold identically for the serial baseline and the M-worker
stale-snapshot pool on the same offered work.
"""
import os
import time

import pytest

from nomad_tpu import fault
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import Worker, stale_snapshot_enabled
from nomad_tpu.structs import structs as s


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def make_node(i, cpu=4000, mem=8192):
    return s.Node(
        id=f"mw-node-{i:04d}", datacenter="dc1", name=f"mw-node-{i:04d}",
        attributes={"kernel.name": "linux", "driver.exec": "1"},
        resources=s.Resources(cpu=cpu, memory_mb=mem, disk_mb=100 * 1024,
                              iops=1000),
        reserved=s.Resources(), status=s.NODE_STATUS_READY)


def make_job(n, count=1, cpu=100, mem=128, priority=50):
    jid = f"mw-job-{n:05d}"
    return s.Job(
        region="global", id=jid, name=jid, type=s.JOB_TYPE_SERVICE,
        priority=priority, datacenters=["dc1"],
        task_groups=[s.TaskGroup(
            name="tg", count=count,
            ephemeral_disk=s.EphemeralDisk(size_mb=10),
            tasks=[s.Task(name="t", driver="exec",
                          config={"command": "/bin/date"},
                          resources=s.Resources(cpu=cpu, memory_mb=mem),
                          log_config=s.LogConfig())])])


def drain(num_workers, n_jobs, stale, nodes=40, count=2, seed=7,
          fault_spec=None, nack_delay=None):
    """Build a server, queue n_jobs while workers are paused, release,
    and wait for every eval to reach a terminal state.  Returns the
    final (allocs, evals, node map, server-stats snapshot)."""
    prev = os.environ.get("NOMAD_TPU_STALE_SNAPSHOT")
    os.environ["NOMAD_TPU_STALE_SNAPSHOT"] = "1" if stale else "0"
    try:
        srv = Server(ServerConfig(num_schedulers=num_workers,
                                  min_heartbeat_ttl=60))
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_STALE_SNAPSHOT", None)
        else:
            os.environ["NOMAD_TPU_STALE_SNAPSHOT"] = prev
    if nack_delay is not None:
        srv.eval_broker.initial_nack_delay = nack_delay
    srv.start()
    try:
        assert wait_until(srv.is_leader, timeout=10.0)
        for i in range(nodes):
            srv.node_register(make_node(i))
        for w in srv.workers:
            w.set_pause(True)
        eval_ids = []
        for n in range(n_jobs):
            _, eid = srv.job_register(make_job(n, count=count))
            eval_ids.append(eid)
        if fault_spec is not None:
            fault.arm(fault_spec)
        for w in srv.workers:
            w.set_pause(False)
        assert wait_until(
            lambda: all(
                (ev := srv.state.eval_by_id(None, eid)) is not None
                and ev.terminal_status() for eid in eval_ids),
            timeout=120.0), "evals did not all reach a terminal state"
        allocs = [a for a in srv.state.allocs(None)
                  if not a.terminal_status()]
        evals = [srv.state.eval_by_id(None, eid) for eid in eval_ids]
        node_map = {n.id: n for n in srv.state.nodes(None)}
        latest = srv.metrics.sink.latest()
        latest["fault_trace"] = list(fault.trace()) if fault_spec else []
        return allocs, evals, node_map, latest
    finally:
        if fault_spec is not None:
            fault.disarm()
        srv.shutdown()


def assert_drain_invariants(allocs, evals, node_map, n_jobs, count):
    # Every eval completed (none failed/cancelled: capacity is ample).
    assert all(ev.status == s.EVAL_STATUS_COMPLETE for ev in evals)
    # Every job placed EXACTLY count allocs: no lost evals, no double
    # placements (unique ids AND unique alloc names per job).
    by_job = {}
    for a in allocs:
        by_job.setdefault(a.job_id, []).append(a)
    assert len(by_job) == n_jobs
    for job_id, job_allocs in by_job.items():
        assert len(job_allocs) == count, \
            f"{job_id}: {len(job_allocs)} allocs (want {count})"
        assert len({a.id for a in job_allocs}) == count
        assert len({a.name for a in job_allocs}) == count
    # Zero overcommit: per-node usage within capacity.
    usage = {}
    for a in allocs:
        res = a.resources
        cpu, mem = usage.get(a.node_id, (0, 0))
        usage[a.node_id] = (cpu + res.cpu, mem + res.memory_mb)
    for node_id, (cpu, mem) in usage.items():
        node = node_map[node_id]
        assert cpu <= node.resources.cpu - node.reserved.cpu
        assert mem <= node.resources.memory_mb - node.reserved.memory_mb


class TestMultiWorkerDrain:
    N_JOBS = 60
    COUNT = 2

    def test_serial_baseline_invariants(self):
        allocs, evals, nodes, _ = drain(1, self.N_JOBS, stale=False,
                                        seed=7)
        assert_drain_invariants(allocs, evals, nodes, self.N_JOBS,
                                self.COUNT)

    def test_m4_stale_snapshot_parity_with_serial(self):
        """M=4 stale-snapshot workers produce the same final cluster
        outcome as the serial path: all jobs fully placed once, zero
        overcommit, every eval complete — with the stale-snapshot cache
        actually exercised (reuse counter nonzero under the queued
        backlog)."""
        allocs, evals, nodes, latest = drain(4, self.N_JOBS, stale=True,
                                             seed=7)
        assert_drain_invariants(allocs, evals, nodes, self.N_JOBS,
                                self.COUNT)
        totals = latest.get("CounterTotals", {})
        if stale_snapshot_enabled():
            assert totals.get("nomad.worker.snapshot_reuse", 0) > 0

    @pytest.mark.chaos
    def test_m4_worker_crash_mid_eval_redelivers_without_loss(self):
        """Chaos variant: injected plan-apply crashes burn deliveries
        mid-drain across the M=4 pool; the broker redelivers and the
        final state still satisfies every drain invariant (no lost
        evals, no double placements)."""
        spec = {"seed": 33, "faults": [
            {"point": "plan.apply", "action": "crash", "prob": 0.1,
             "times": 6}]}
        allocs, evals, nodes, latest = drain(
            4, self.N_JOBS, stale=True, seed=33, fault_spec=spec,
            nack_delay=0.05)
        assert_drain_invariants(allocs, evals, nodes, self.N_JOBS,
                                self.COUNT)
        # The injection actually fired (otherwise this test is the
        # parity test again).
        assert any(point == "plan.apply"
                   for point, _, _ in latest["fault_trace"])


class TestConflictRequeue:
    def test_capacity_conflict_partially_commits_and_replans(self):
        """Two stale-snapshot workers planning onto the same nearly-full
        node: the loser's plan partially commits, the scheduler replans
        off refreshed state, and nothing overcommits.  Deterministic
        shape: ONE node that fits exactly one alloc at a time, two jobs
        racing."""
        prev = os.environ.get("NOMAD_TPU_STALE_SNAPSHOT")
        os.environ["NOMAD_TPU_STALE_SNAPSHOT"] = "1"
        try:
            srv = Server(ServerConfig(num_schedulers=2,
                                      min_heartbeat_ttl=60))
        finally:
            if prev is None:
                os.environ.pop("NOMAD_TPU_STALE_SNAPSHOT", None)
            else:
                os.environ["NOMAD_TPU_STALE_SNAPSHOT"] = prev
        srv.start()
        try:
            assert wait_until(srv.is_leader, timeout=10.0)
            # One node, room for exactly two 400-cpu allocs.
            srv.node_register(make_node(0, cpu=900, mem=2048))
            for w in srv.workers:
                w.set_pause(True)
            ids = []
            for n in range(2):
                _, eid = srv.job_register(make_job(n, count=1, cpu=400,
                                                   mem=256))
                ids.append(eid)
            for w in srv.workers:
                w.set_pause(False)
            assert wait_until(
                lambda: all(
                    (ev := srv.state.eval_by_id(None, eid)) is not None
                    and ev.terminal_status() for eid in ids),
                timeout=60.0)
            allocs = [a for a in srv.state.allocs(None)
                      if not a.terminal_status()]
            assert len(allocs) == 2
            assert sum(a.resources.cpu for a in allocs) <= 900
        finally:
            srv.shutdown()
