"""Deployment structs + state-store surface (reference:
nomad/structs/structs.go:3698-3795, nomad/state/state_store.go:219-345 —
at this reference version the scheduler never creates deployments; the
struct + store contract is the parity target)."""

from nomad_tpu.state import StateStore
from nomad_tpu.structs import structs as s


def _dep(job_id="job-1", status=s.DEPLOYMENT_STATUS_RUNNING):
    return s.Deployment(
        id=s.generate_uuid(),
        job_id=job_id,
        job_version=3,
        task_groups={"web": s.DeploymentState(desired_total=5,
                                              placed_allocs=2)},
        status=status,
    )


class TestDeployments:
    def test_upsert_get_list(self):
        store = StateStore()
        d = _dep()
        store.upsert_deployment(10, d)
        got = store.deployment_by_id(None, d.id)
        assert got.job_id == "job-1"
        assert got.create_index == 10 and got.modify_index == 10
        assert got.task_groups["web"].desired_total == 5
        assert got.active()
        assert [x.id for x in store.deployments(None)] == [d.id]
        assert store.table_index("deployment") == 10

    def test_cancel_prior(self):
        store = StateStore()
        old = _dep()
        store.upsert_deployment(10, old)
        newer = _dep()
        store.upsert_deployment(11, newer, cancel_prior=True)
        got_old = store.deployment_by_id(None, old.id)
        assert got_old.status == s.DEPLOYMENT_STATUS_CANCELLED
        assert not got_old.active()
        assert store.deployment_by_id(None, newer.id).active()
        # Latest by create index is the newer one.
        assert store.latest_deployment_by_job(None, "job-1").id == newer.id

    def test_status_update_and_delete(self):
        store = StateStore()
        d = _dep()
        store.upsert_deployment(10, d)
        store.update_deployment_status(11, s.DeploymentStatusUpdate(
            deployment_id=d.id, status=s.DEPLOYMENT_STATUS_SUCCESSFUL,
            status_description="done"))
        got = store.deployment_by_id(None, d.id)
        assert got.status == s.DEPLOYMENT_STATUS_SUCCESSFUL
        assert got.status_description == "done"
        store.delete_deployment(12, d.id)
        assert store.deployment_by_id(None, d.id) is None

    def test_snapshot_isolated_and_persist_roundtrip(self):
        store = StateStore()
        d = _dep()
        store.upsert_deployment(10, d)
        snap = store.snapshot()
        store.update_deployment_status(11, s.DeploymentStatusUpdate(
            deployment_id=d.id, status=s.DEPLOYMENT_STATUS_FAILED))
        assert snap.deployment_by_id(None, d.id).status == \
            s.DEPLOYMENT_STATUS_RUNNING

        blob = store.persist()
        restored = StateStore.restore(blob)
        assert restored.deployment_by_id(None, d.id).status == \
            s.DEPLOYMENT_STATUS_FAILED

    def test_blocking_query_watch_fires(self):
        store = StateStore()
        from nomad_tpu.state.state_store import WatchSet

        ws = WatchSet()
        ws.add(store, "deployment")
        store.upsert_deployment(10, _dep())
        # watch() returns False when a watched table advanced (True only
        # on timeout) — the upsert must wake the watcher.
        assert ws.watch(timeout=2.0) is False
