"""L0 tests: fit & scoring functions (reference: nomad/structs/funcs_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.structs import structs as s
from nomad_tpu.structs.funcs import (
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)


def _bare_node(cpu=2000, mem=2048, disk=10000, iops=100):
    return s.Node(
        id=s.generate_uuid(),
        resources=s.Resources(cpu=cpu, memory_mb=mem, disk_mb=disk, iops=iops),
        status=s.NODE_STATUS_READY,
    )


def _alloc_with(cpu, mem, disk=0, iops=0):
    return s.Allocation(
        id=s.generate_uuid(),
        resources=s.Resources(cpu=cpu, memory_mb=mem, disk_mb=disk, iops=iops),
    )


class TestRemoveAllocs:
    def test_removes_by_id(self):
        a1, a2, a3 = _alloc_with(1, 1), _alloc_with(2, 2), _alloc_with(3, 3)
        out = remove_allocs([a1, a2, a3], [a2])
        assert [a.id for a in out] == [a1.id, a3.id]

    def test_empty_remove(self):
        a1 = _alloc_with(1, 1)
        assert remove_allocs([a1], []) == [a1]


class TestFilterTerminalAllocs:
    def test_splits_terminal(self):
        live = _alloc_with(1, 1)
        dead = _alloc_with(2, 2)
        dead.name = "x"
        dead.desired_status = s.ALLOC_DESIRED_STATUS_STOP
        out, terminal = filter_terminal_allocs([live, dead])
        assert out == [live]
        assert terminal["x"] is dead

    def test_keeps_latest_terminal_per_name(self):
        old = _alloc_with(1, 1)
        old.name = "x"
        old.desired_status = s.ALLOC_DESIRED_STATUS_STOP
        old.create_index = 5
        new = _alloc_with(1, 1)
        new.name = "x"
        new.desired_status = s.ALLOC_DESIRED_STATUS_STOP
        new.create_index = 10
        _, terminal = filter_terminal_allocs([old, new])
        assert terminal["x"] is new

    def test_client_status_terminal(self):
        a = _alloc_with(1, 1)
        a.client_status = s.ALLOC_CLIENT_STATUS_FAILED
        out, _ = filter_terminal_allocs([a])
        assert out == []


class TestAllocsFit:
    def test_fits(self):
        node = _bare_node()
        fit, dim, used = allocs_fit(node, [_alloc_with(1000, 1024)])
        assert fit, dim
        assert used.cpu == 1000
        assert used.memory_mb == 1024

    def test_cpu_exhausted(self):
        node = _bare_node(cpu=500)
        fit, dim, _ = allocs_fit(node, [_alloc_with(1000, 100)])
        assert not fit
        assert dim == "cpu exhausted"

    def test_memory_exhausted(self):
        node = _bare_node(mem=100)
        fit, dim, _ = allocs_fit(node, [_alloc_with(100, 1000)])
        assert not fit
        assert dim == "memory exhausted"

    def test_reserved_counts(self):
        node = _bare_node(cpu=1000)
        node.reserved = s.Resources(cpu=600)
        fit, dim, _ = allocs_fit(node, [_alloc_with(500, 10)])
        assert not fit
        assert dim == "cpu exhausted"

    def test_task_resources_summed(self):
        node = _bare_node()
        a = s.Allocation(
            id=s.generate_uuid(),
            shared_resources=s.Resources(disk_mb=100),
            task_resources={
                "a": s.Resources(cpu=300, memory_mb=100),
                "b": s.Resources(cpu=400, memory_mb=200),
            },
        )
        fit, _, used = allocs_fit(node, [a])
        assert fit
        assert used.cpu == 700
        assert used.memory_mb == 300
        assert used.disk_mb == 100

    def test_no_resources_raises(self):
        node = _bare_node()
        with pytest.raises(ValueError):
            allocs_fit(node, [s.Allocation(id="x")])

    def test_mock_node_port_collision(self):
        """Two allocs reserving the same port on the same IP collide."""
        node = mock.node()
        a1 = mock.alloc()
        a2 = mock.alloc()
        # strip combined resources so task_resources (with ports) are used
        a1.resources = None
        a2.resources = None
        fit, dim, _ = allocs_fit(node, [a1, a2])
        assert not fit
        assert dim == "reserved port collision"


class TestScoreFit:
    def test_perfect_fit_scores_18(self):
        node = _bare_node(cpu=4096, mem=8192)
        util = s.Resources(cpu=4096, memory_mb=8192)
        assert score_fit(node, util) == pytest.approx(18.0)

    def test_empty_node_scores_0(self):
        node = _bare_node(cpu=4096, mem=8192)
        assert score_fit(node, s.Resources()) == pytest.approx(0.0)

    def test_half_fit(self):
        node = _bare_node(cpu=4096, mem=8192)
        util = s.Resources(cpu=2048, memory_mb=4096)
        # 20 - 2*10^0.5
        assert score_fit(node, util) == pytest.approx(20.0 - 2 * 10 ** 0.5)

    def test_reserved_shrinks_capacity(self):
        node = _bare_node(cpu=2000, mem=2000)
        node.reserved = s.Resources(cpu=1000, memory_mb=1000)
        util = s.Resources(cpu=1000, memory_mb=1000)
        # free fraction = 1 - 1000/1000 = 0 → perfect fit → 18
        assert score_fit(node, util) == pytest.approx(18.0)

    def test_monotonic_in_utilization(self):
        node = _bare_node(cpu=4000, mem=4000)
        scores = [
            score_fit(node, s.Resources(cpu=c, memory_mb=c))
            for c in (0, 1000, 2000, 3000, 4000)
        ]
        assert scores == sorted(scores)
