"""Vectorized system scheduler (ops/system_batch.py) vs the oracle
SystemScheduler: identical placements on the happy path, oracle fallback
parity on filtered/exhausted clusters."""

import pytest

from nomad_tpu import mock
from nomad_tpu.ops.system_batch import new_tpu_system_scheduler
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.system import new_system_scheduler
from nomad_tpu.structs import structs as s


def _cluster(h, n, cpu=4000, mem=8192, attrs=None):
    nodes = []
    for i in range(n):
        node = mock.node()
        node.id = f"node-{i:04d}"
        node.resources.networks = []
        node.reserved.networks = []
        node.resources.cpu = cpu
        node.resources.memory_mb = mem
        if attrs:
            node.attributes.update(attrs(i))
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    return nodes


def _system_job(cpu=100, constrained=False):
    job = mock.system_job()
    for tg in job.task_groups:
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = cpu
            t.resources.memory_mb = 64
    if constrained:
        job.task_groups[0].constraints = list(
            job.task_groups[0].constraints) + [
            s.Constraint("${attr.rack}", "r1", "=")]
    return job


def _eval(job):
    return s.Evaluation(
        id=s.generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
        status=s.EVAL_STATUS_PENDING)


def _run(factory, n_nodes, job_fn, attrs=None):
    h = Harness()
    _cluster(h, n_nodes, attrs=attrs)
    job = job_fn()
    h.state.upsert_job(h.next_index(), job)
    h.process(factory, job and _eval(job))
    placements = sorted(
        (a.node_id, a.task_group, a.name)
        for a in h.state.allocs_by_job(None, job.id, True))
    ev_status = h.evals[-1].status if h.evals else None
    return h, job, placements, ev_status


class TestSystemBatchDifferential:
    def test_happy_path_identical(self):
        _, _, oracle, st1 = _run(new_system_scheduler, 50, _system_job)
        _, _, fast, st2 = _run(new_tpu_system_scheduler, 50, _system_job)
        assert len(oracle) == len(fast) == 50
        assert [p[0] for p in oracle] == [p[0] for p in fast]
        assert st1 == st2 == s.EVAL_STATUS_COMPLETE

    def test_constraint_filtered_falls_back_identically(self):
        attrs = lambda i: {"rack": "r1" if i % 3 == 0 else "r2"}
        _, _, oracle, _ = _run(
            new_system_scheduler, 30,
            lambda: _system_job(constrained=True), attrs=attrs)
        _, _, fast, _ = _run(
            new_tpu_system_scheduler, 30,
            lambda: _system_job(constrained=True), attrs=attrs)
        assert [p[0] for p in oracle] == [p[0] for p in fast]
        assert len(fast) == 10  # every third node

    def test_exhausted_falls_back_identically(self):
        # Asks bigger than half the node: only 1 fits per node; second
        # task group exhausts → oracle fallback with failure metrics.
        def fat_job():
            job = _system_job(cpu=3500)
            return job

        ha, _, oracle, _ = _run(new_system_scheduler, 5, fat_job)
        hb, _, fast, _ = _run(new_tpu_system_scheduler, 5, fat_job)
        assert oracle == fast

    def test_prev_alloc_chained_on_node_update(self):
        h = Harness()
        _cluster(h, 8)
        job = _system_job()
        h.state.upsert_job(h.next_index(), job)
        h.process(new_tpu_system_scheduler, _eval(job))
        first = {a.node_id: a for a in h.state.allocs_by_job(None, job.id, True)}
        assert len(first) == 8

        # New node arrives: only it gets a placement, existing ones stay.
        node = mock.node()
        node.id = "node-new"
        node.resources.networks = []
        node.reserved.networks = []
        h.state.upsert_node(h.next_index(), node)
        ev = _eval(job)
        ev.triggered_by = s.EVAL_TRIGGER_NODE_UPDATE
        h.process(new_tpu_system_scheduler, ev)
        after = h.state.allocs_by_job(None, job.id, True)
        assert len(after) == 9
        assert sum(1 for a in after if a.node_id == "node-new") == 1

    def test_worker_routes_system_to_vectorized(self, tmp_path):
        from nomad_tpu.server.server import Server, ServerConfig

        cfg = ServerConfig(data_dir=str(tmp_path / "raft"),
                           use_tpu_batch_worker=True)
        srv = Server(cfg)
        srv.start()
        try:
            import time

            for i in range(6):
                node = mock.node()
                node.id = f"n-{i}"
                node.resources.networks = []
                node.reserved.networks = []
                srv.node_register(node)
            job = _system_job()
            srv.job_register(job)
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(srv.state.allocs_by_job(None, job.id, True)) == 6:
                    break
                time.sleep(0.05)
            assert len(srv.state.allocs_by_job(None, job.id, True)) == 6
        finally:
            srv.shutdown()
