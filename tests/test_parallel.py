"""Multi-chip sharding tests on the virtual 8-device CPU mesh
(SURVEY.md §4 item 3: multi-node without a real cluster)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.parallel import (
    make_node_mesh,
    sharded_candidate_scores,
    sharded_placement_rounds,
    sharded_schedule_step,
)
from nomad_tpu.ops.kernels import _score_fit, placement_rounds

# Heavy integration/differential module: quick tier skips it (pytest.ini).
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_node_mesh()


def _mk_problem(n=256, u=4, seed=0):
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], dtype=np.int32), (n, 1))
    used = np.zeros((n, 4), dtype=np.int32)
    used[:, 0] = rng.integers(0, 2000, n)
    used[:, 1] = rng.integers(0, 4096, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = rng.random((u, n)) < 0.8
    ask = np.tile(np.array([500, 256, 150, 0], dtype=np.int32), (u, 1))
    count = np.full(u, 20, dtype=np.int32)
    return feas, used, capacity, denom, ask, count


def test_sharded_scores_match_single_device(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem()
    k = 16
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=k)
    scores, idx = np.asarray(scores), np.asarray(idx)
    assert scores.shape == (4, k * 8)
    # Every candidate's score must equal the single-device score at that node.
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        for c in range(k * 8):
            n_idx = idx[u_i, c]
            if scores[u_i, c] > -1e29:
                assert ok[n_idx]
                assert scores[u_i, c] == pytest.approx(full[n_idx], abs=1e-4)


def test_sharded_topk_contains_global_best(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=3)
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=16)
    scores, idx = np.asarray(scores), np.asarray(idx)
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        masked = np.where(ok, full, -np.inf)
        best_node = int(np.argmax(masked))
        assert best_node in idx[u_i], "global best node missing from candidates"


def _mk_full_problem(n=256, u=12, j=6, seed=11, tight=False):
    """Non-trivial problem: multiple specs per job (anti-affinity collisions
    matter), distinct_hosts on some specs, pre-existing job counts, and
    counts high enough to need capacity feedback across specs."""
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], dtype=np.int32), (n, 1))
    used = np.zeros((n, 4), dtype=np.int32)
    used[:, 0] = rng.integers(0, 3000 if tight else 2000, n)
    used[:, 1] = rng.integers(0, 6144 if tight else 4096, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = rng.random((u, n)) < 0.7
    ask = np.stack([
        np.array([rng.integers(200, 900), rng.integers(128, 1024), 150, 0],
                 dtype=np.int32)
        for _ in range(u)
    ])
    count = rng.integers(4, 24, u).astype(np.int32)
    penalty = np.where(rng.random(u) < 0.5, 20.0, 10.0).astype(np.float32)
    distinct = rng.random(u) < 0.3
    job_index = rng.integers(0, j, u).astype(np.int32)
    job_counts = (rng.random((j, n)) < 0.05).astype(np.int32)
    return (feas, used, capacity, denom, ask, count, penalty, distinct,
            job_index, job_counts)


@pytest.mark.parametrize("seed,tight,k_cand", [
    (11, False, 8),   # k_cand·D = 64 < N=256: real local-top-k truncation
    (23, True, 16),   # tight capacity + truncation
    (57, False, 32),  # full candidate set (k_cand·D == N)
])
def test_sharded_placements_equal_single_chip(mesh, seed, tight, k_cand):
    """Differential test (VERDICT r1 item 2): the node-sharded kernel must
    produce *identical* placements to the single-chip kernel — same
    anti-affinity, distinct_hosts, job_counts, and round-loop semantics.
    k_cand < N/D cases exercise the local top-k candidate truncation (the
    kernel's only approximation axis); counts stay ≤ k_cand so equality is
    guaranteed."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=seed, tight=tight)
    count = np.minimum(count, k_cand)  # equality guarantee: commit ≤ k_cand
    key = jax.random.PRNGKey(seed)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key)

    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=k_cand)

    np.testing.assert_array_equal(
        np.asarray(shard.placements), np.asarray(single.placements))
    np.testing.assert_array_equal(
        np.asarray(shard.unplaced), np.asarray(single.unplaced))
    np.testing.assert_array_equal(
        np.asarray(shard.used_after), np.asarray(single.used_after))
    # sanity: the problem actually exercised the semantics
    assert np.asarray(single.placements).sum() > 0
    assert np.asarray(single.rounds) >= 1


def test_sharded_distinct_hosts_and_anti_affinity(mesh):
    """Distinct-hosts specs never land on a node that already holds an alloc
    of the same job; anti-affinity spreads same-job specs."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=99)
    distinct[:] = True
    result = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), jax.random.PRNGKey(7), k_cand=32)
    placements = np.asarray(result.placements)
    # per (job, node): existing count + all placements of that job ≤ 1 + ...
    # distinct_hosts ⇒ a spec's placements avoid nodes with prior job allocs,
    # and no node receives two allocs of the same job in total.
    j = job_counts.shape[0]
    for ji in range(j):
        total = job_counts[ji].copy()
        for u_i in np.where(job_index == ji)[0]:
            total = total + placements[u_i]
        assert total.max() <= 1, f"job {ji} violated distinct_hosts"


def test_sharded_schedule_step_end_to_end(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=5)
    placements, used_after = sharded_schedule_step(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count), k=16)
    placements = np.asarray(placements)
    used_after = np.asarray(used_after)
    # all counts placed (capacity is ample)
    assert placements.sum() == count.sum()
    # no overcommit on any node/dim
    assert np.all(used_after <= capacity)
    # placements only on feasible nodes
    for u_i in range(4):
        assert np.all(feas[u_i][placements[u_i] > 0])


def _mk_net_tensors(n, u, seed=0, w=4):
    """Small-port-space NetTensors: per-spec bandwidth/reserved-port/dyn
    asks + per-node port state (mirrors ops/kernels.NetTensors shapes)."""
    import jax.numpy as jnp

    from nomad_tpu.ops.kernels import NetTensors

    rng = np.random.default_rng(seed)
    active = rng.random(u) < 0.7
    mbits = np.where(active, rng.integers(10, 200, u), 0).astype(np.int32)
    dyn_need = np.where(active, rng.integers(0, 3, u), 0).astype(np.int32)
    resv_words = np.zeros((u, w), dtype=np.uint32)
    for i in range(u):
        if active[i] and rng.random() < 0.6:
            bit = int(rng.integers(0, 32 * w))
            resv_words[i, bit // 32] |= np.uint32(1 << (bit % 32))
    bw_cap = rng.integers(100, 1000, n).astype(np.int32)
    bw_cap[rng.random(n) < 0.1] = -1           # no network device
    bw_used = rng.integers(0, 100, n).astype(np.int32)
    dyn_free = rng.integers(0, 50, n).astype(np.int32)
    port_words = np.zeros((n, w), dtype=np.uint32)
    for i in range(n):
        for _ in range(int(rng.integers(0, 4))):
            bit = int(rng.integers(0, 32 * w))
            port_words[i, bit // 32] |= np.uint32(1 << (bit % 32))
    return NetTensors(
        active=jnp.asarray(active), mbits=jnp.asarray(mbits),
        dyn_need=jnp.asarray(dyn_need), resv_words=jnp.asarray(resv_words),
        bw_cap=jnp.asarray(bw_cap), bw_used=jnp.asarray(bw_used),
        dyn_free=jnp.asarray(dyn_free), port_words=jnp.asarray(port_words))


def _mk_dp_tensors(n, u, seed=0, v=16, k_attr=2):
    """DPTensors: per-spec distinct_property columns + used-value bitsets
    over a small interned value space."""
    import jax.numpy as jnp

    from nomad_tpu.ops.encode import MISSING
    from nomad_tpu.ops.kernels import DPTensors

    rng = np.random.default_rng(seed)
    col = rng.integers(0, k_attr, u).astype(np.int32)
    active = rng.random(u) < 0.6
    used0 = (rng.random((u, v)) < 0.15)
    attr = rng.integers(0, v, (n, k_attr)).astype(np.int32)
    attr[rng.random((n, k_attr)) < 0.05] = MISSING
    return DPTensors(col=jnp.asarray(col), active=jnp.asarray(active),
                     used0=jnp.asarray(used0), attr_values=jnp.asarray(attr))


@pytest.mark.parametrize("seed", [3, 17])
def test_sharded_networks_equal_single_chip(mesh, seed):
    """Feature parity (VERDICT r2 item 3): bandwidth, reserved-port and
    dynamic-capacity accounting on the sharded path must produce the
    SAME placements as the single-chip kernel."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=seed)
    count = np.minimum(count, 16)
    u, n = feas.shape
    net = _mk_net_tensors(n, u, seed=seed)
    key = jax.random.PRNGKey(seed)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, net=net)
    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=16, net=net)

    np.testing.assert_array_equal(
        np.asarray(shard.placements), np.asarray(single.placements))
    np.testing.assert_array_equal(
        np.asarray(shard.unplaced), np.asarray(single.unplaced))
    assert np.asarray(single.placements).sum() > 0


@pytest.mark.parametrize("seed", [5, 29])
def test_sharded_distinct_property_equal_single_chip(mesh, seed):
    """distinct_property parity: the cross-shard best-per-value dedup
    (pmax/pmin) must keep exactly the winner the single-chip
    scatter-max/min picks, including global-node-index tie-breaks."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=seed)
    count = np.minimum(count, 16)
    u, n = feas.shape
    dp = _mk_dp_tensors(n, u, seed=seed)
    key = jax.random.PRNGKey(seed)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, dp=dp)
    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=16, dp=dp)

    np.testing.assert_array_equal(
        np.asarray(shard.placements), np.asarray(single.placements))
    np.testing.assert_array_equal(
        np.asarray(shard.unplaced), np.asarray(single.unplaced))
    placed_dp = np.asarray(
        single.placements)[np.asarray(dp.active)].sum()
    assert placed_dp > 0, "no dp-active spec placed; test is vacuous"


def test_sharded_under_commit_converges_to_single_chip(mesh):
    """k_cand under-commit path (VERDICT r2 item 3): a spec needing more
    than k_cand·D placements per round under-commits and finishes over
    later rounds.  Each round contributes at most k_cand nodes PER SHARD,
    so a shard holding more than k_cand x rounds of the global top-count
    legitimately trades those slots to other shards' next-best nodes —
    the under-commit result is an approximation, not a bit-copy.  What
    must hold exactly: full placement (ample capacity), exact unplaced
    accounting, no overcommit, and bin-pack quality within the 0.5%
    budget of the single-chip kernel's global top-count selection."""
    n, u = 1024, 1
    rng = np.random.default_rng(41)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], np.int32), (n, 1))
    used = np.zeros((n, 4), np.int32)
    # Distinct per-node usage ⇒ distinct binpack scores ⇒ no f32 ties.
    used[:, 0] = rng.permutation(n) * 3
    used[:, 1] = rng.permutation(n) * 4
    denom = capacity[:, :2].astype(np.float32)
    feas = (rng.random((u, n)) < 0.9)
    ask = np.array([[500, 256, 150, 0]], np.int32)
    count = np.array([300], np.int32)          # ≫ k_cand·D = 64
    penalty = np.array([20.0], np.float32)
    distinct = np.zeros(u, bool)
    job_index = np.zeros(u, np.int32)
    job_counts = np.zeros((u, n), np.int32)
    key = jax.random.PRNGKey(13)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key)
    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=8)

    assert int(np.asarray(shard.rounds)) > int(np.asarray(single.rounds)), \
        "under-commit path not exercised (increase count or drop k_cand)"
    placements = np.asarray(shard.placements)
    np.testing.assert_array_equal(
        np.asarray(shard.unplaced), np.asarray(single.unplaced))
    assert placements.sum() == int(np.asarray(single.placements).sum()) == 300
    assert np.all(np.asarray(shard.used_after) <= capacity)

    def quality(used_after_arr):
        frac = 1.0 - used_after_arr[:, :2].astype(np.float64) / denom
        score = 20.0 - (10.0 ** frac[:, 0] + 10.0 ** frac[:, 1])
        return np.clip(score, 0.0, 18.0).sum()

    q_single = quality(np.asarray(single.used_after))
    q_shard = quality(np.asarray(shard.used_after))
    assert q_shard >= 0.995 * q_single


def test_sharded_contended_multi_round_at_4k_nodes(mesh):
    """Contended multi-round workload at 4k virtual nodes (VERDICT r2
    item 3): many specs compete for scarce capacity across rounds.  The
    sharded result must respect every invariant (no overcommit, exact
    unplaced accounting, distinct_hosts) and its bin-pack quality must
    track the single-chip kernel."""
    n, u, j = 4096, 24, 8
    rng = np.random.default_rng(77)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], np.int32), (n, 1))
    used = np.zeros((n, 4), np.int32)
    used[:, 0] = rng.integers(1000, 3500, n)   # 80-95% contended fleet
    used[:, 1] = rng.integers(2048, 7168, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = (rng.random((u, n)) < 0.8)
    ask = np.stack([
        np.array([rng.integers(300, 800), rng.integers(256, 1024), 150, 0],
                 np.int32) for _ in range(u)])
    count = rng.integers(64, 256, u).astype(np.int32)
    penalty = np.full(u, 20.0, np.float32)
    distinct = rng.random(u) < 0.25
    job_index = rng.integers(0, j, u).astype(np.int32)
    job_counts = np.zeros((j, n), np.int32)
    key = jax.random.PRNGKey(19)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key)
    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=16)

    placements = np.asarray(shard.placements)
    used_after = np.asarray(shard.used_after)
    # Exact accounting: capacity respected, unplaced + placed == count.
    assert np.all(used_after <= capacity)
    np.testing.assert_array_equal(
        placements.sum(axis=1) + np.asarray(shard.unplaced),
        count)
    # distinct_hosts respected
    for u_i in np.where(distinct)[0]:
        assert placements[u_i].max() <= 1
    # Same total throughput and bin-pack quality within the 0.5% budget
    # of the single-chip kernel (ordering may differ under contention
    # when specs exceed k_cand·D per round).
    single_placed = int(np.asarray(single.placements).sum())
    shard_placed = int(placements.sum())
    assert shard_placed >= 0.995 * single_placed

    def quality(used_after_arr):
        frac = 1.0 - used_after_arr[:, :2].astype(np.float64) / denom
        score = 20.0 - (10.0 ** frac[:, 0] + 10.0 ** frac[:, 1])
        return np.clip(score, 0.0, 18.0).sum()

    q_single = quality(np.asarray(single.used_after))
    q_shard = quality(used_after)
    assert q_shard >= 0.995 * q_single


def test_driver_dryrun_composition(mesh):
    """Pin the EXACT composition the driver's multichip artifact runs —
    ``jax.jit`` over ``functools.partial(sharded_placement_rounds, mesh)``
    with the dryrun's shapes — so a regression in that path (r03: the
    artifact hung while the direct-call tests stayed green) fails in CI,
    not in the driver. Deadline-guarded: a recurrence of the hang must
    FAIL here, not stall the suite."""
    import signal

    import __graft_entry__ as g  # repo root is on sys.path via conftest

    def _timeout(signum, frame):
        raise TimeoutError("dryrun composition exceeded 120s — "
                           "the r03 hang is back")

    old = signal.signal(signal.SIGALRM, _timeout)
    signal.alarm(120)
    try:
        g._dryrun_multichip_impl(8)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
