"""Multi-chip sharding tests on the virtual 8-device CPU mesh
(SURVEY.md §4 item 3: multi-node without a real cluster)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.parallel import (
    make_node_mesh,
    sharded_candidate_scores,
    sharded_schedule_step,
)
from nomad_tpu.ops.kernels import _score_fit


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_node_mesh()


def _mk_problem(n=256, u=4, seed=0):
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], dtype=np.int32), (n, 1))
    used = np.zeros((n, 4), dtype=np.int32)
    used[:, 0] = rng.integers(0, 2000, n)
    used[:, 1] = rng.integers(0, 4096, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = rng.random((u, n)) < 0.8
    ask = np.tile(np.array([500, 256, 150, 0], dtype=np.int32), (u, 1))
    count = np.full(u, 20, dtype=np.int32)
    return feas, used, capacity, denom, ask, count


def test_sharded_scores_match_single_device(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem()
    k = 16
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=k)
    scores, idx = np.asarray(scores), np.asarray(idx)
    assert scores.shape == (4, k * 8)
    # Every candidate's score must equal the single-device score at that node.
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        for c in range(k * 8):
            n_idx = idx[u_i, c]
            if scores[u_i, c] > -1e29:
                assert ok[n_idx]
                assert scores[u_i, c] == pytest.approx(full[n_idx], abs=1e-4)


def test_sharded_topk_contains_global_best(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=3)
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=16)
    scores, idx = np.asarray(scores), np.asarray(idx)
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        masked = np.where(ok, full, -np.inf)
        best_node = int(np.argmax(masked))
        assert best_node in idx[u_i], "global best node missing from candidates"


def test_sharded_schedule_step_end_to_end(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=5)
    placements, used_after = sharded_schedule_step(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count), k=16)
    placements = np.asarray(placements)
    used_after = np.asarray(used_after)
    # all counts placed (capacity is ample)
    assert placements.sum() == count.sum()
    # no overcommit on any node/dim
    assert np.all(used_after <= capacity)
    # placements only on feasible nodes
    for u_i in range(4):
        assert np.all(feas[u_i][placements[u_i] > 0])
