"""Multi-chip sharding tests on the virtual 8-device CPU mesh
(SURVEY.md §4 item 3: multi-node without a real cluster)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.parallel import (
    make_node_mesh,
    sharded_candidate_scores,
    sharded_placement_rounds,
    sharded_schedule_step,
)
from nomad_tpu.ops.kernels import _score_fit, placement_rounds


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_node_mesh()


def _mk_problem(n=256, u=4, seed=0):
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], dtype=np.int32), (n, 1))
    used = np.zeros((n, 4), dtype=np.int32)
    used[:, 0] = rng.integers(0, 2000, n)
    used[:, 1] = rng.integers(0, 4096, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = rng.random((u, n)) < 0.8
    ask = np.tile(np.array([500, 256, 150, 0], dtype=np.int32), (u, 1))
    count = np.full(u, 20, dtype=np.int32)
    return feas, used, capacity, denom, ask, count


def test_sharded_scores_match_single_device(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem()
    k = 16
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=k)
    scores, idx = np.asarray(scores), np.asarray(idx)
    assert scores.shape == (4, k * 8)
    # Every candidate's score must equal the single-device score at that node.
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        for c in range(k * 8):
            n_idx = idx[u_i, c]
            if scores[u_i, c] > -1e29:
                assert ok[n_idx]
                assert scores[u_i, c] == pytest.approx(full[n_idx], abs=1e-4)


def test_sharded_topk_contains_global_best(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=3)
    scores, idx = sharded_candidate_scores(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), k=16)
    scores, idx = np.asarray(scores), np.asarray(idx)
    for u_i in range(4):
        full = np.asarray(_score_fit(
            jnp.asarray(used), jnp.asarray(ask[u_i]), jnp.asarray(denom)))
        cap_left = capacity - used
        fits = np.all(ask[u_i][None, :] <= cap_left, axis=1)
        ok = feas[u_i] & fits
        masked = np.where(ok, full, -np.inf)
        best_node = int(np.argmax(masked))
        assert best_node in idx[u_i], "global best node missing from candidates"


def _mk_full_problem(n=256, u=12, j=6, seed=11, tight=False):
    """Non-trivial problem: multiple specs per job (anti-affinity collisions
    matter), distinct_hosts on some specs, pre-existing job counts, and
    counts high enough to need capacity feedback across specs."""
    rng = np.random.default_rng(seed)
    capacity = np.tile(np.array([4000, 8192, 102400, 150], dtype=np.int32), (n, 1))
    used = np.zeros((n, 4), dtype=np.int32)
    used[:, 0] = rng.integers(0, 3000 if tight else 2000, n)
    used[:, 1] = rng.integers(0, 6144 if tight else 4096, n)
    denom = capacity[:, :2].astype(np.float32)
    feas = rng.random((u, n)) < 0.7
    ask = np.stack([
        np.array([rng.integers(200, 900), rng.integers(128, 1024), 150, 0],
                 dtype=np.int32)
        for _ in range(u)
    ])
    count = rng.integers(4, 24, u).astype(np.int32)
    penalty = np.where(rng.random(u) < 0.5, 20.0, 10.0).astype(np.float32)
    distinct = rng.random(u) < 0.3
    job_index = rng.integers(0, j, u).astype(np.int32)
    job_counts = (rng.random((j, n)) < 0.05).astype(np.int32)
    return (feas, used, capacity, denom, ask, count, penalty, distinct,
            job_index, job_counts)


@pytest.mark.parametrize("seed,tight,k_cand", [
    (11, False, 8),   # k_cand·D = 64 < N=256: real local-top-k truncation
    (23, True, 16),   # tight capacity + truncation
    (57, False, 32),  # full candidate set (k_cand·D == N)
])
def test_sharded_placements_equal_single_chip(mesh, seed, tight, k_cand):
    """Differential test (VERDICT r1 item 2): the node-sharded kernel must
    produce *identical* placements to the single-chip kernel — same
    anti-affinity, distinct_hosts, job_counts, and round-loop semantics.
    k_cand < N/D cases exercise the local top-k candidate truncation (the
    kernel's only approximation axis); counts stay ≤ k_cand so equality is
    guaranteed."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=seed, tight=tight)
    count = np.minimum(count, k_cand)  # equality guarantee: commit ≤ k_cand
    key = jax.random.PRNGKey(seed)

    single = placement_rounds(
        jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key)

    shard = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), key, k_cand=k_cand)

    np.testing.assert_array_equal(
        np.asarray(shard.placements), np.asarray(single.placements))
    np.testing.assert_array_equal(
        np.asarray(shard.unplaced), np.asarray(single.unplaced))
    np.testing.assert_array_equal(
        np.asarray(shard.used_after), np.asarray(single.used_after))
    # sanity: the problem actually exercised the semantics
    assert np.asarray(single.placements).sum() > 0
    assert np.asarray(single.rounds) >= 1


def test_sharded_distinct_hosts_and_anti_affinity(mesh):
    """Distinct-hosts specs never land on a node that already holds an alloc
    of the same job; anti-affinity spreads same-job specs."""
    (feas, used, capacity, denom, ask, count, penalty, distinct,
     job_index, job_counts) = _mk_full_problem(seed=99)
    distinct[:] = True
    result = sharded_placement_rounds(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count),
        jnp.asarray(penalty), jnp.asarray(distinct), jnp.asarray(job_index),
        jnp.asarray(job_counts), jax.random.PRNGKey(7), k_cand=32)
    placements = np.asarray(result.placements)
    # per (job, node): existing count + all placements of that job ≤ 1 + ...
    # distinct_hosts ⇒ a spec's placements avoid nodes with prior job allocs,
    # and no node receives two allocs of the same job in total.
    j = job_counts.shape[0]
    for ji in range(j):
        total = job_counts[ji].copy()
        for u_i in np.where(job_index == ji)[0]:
            total = total + placements[u_i]
        assert total.max() <= 1, f"job {ji} violated distinct_hosts"


def test_sharded_schedule_step_end_to_end(mesh):
    feas, used, capacity, denom, ask, count = _mk_problem(seed=5)
    placements, used_after = sharded_schedule_step(
        mesh, jnp.asarray(feas), jnp.asarray(used), jnp.asarray(capacity),
        jnp.asarray(denom), jnp.asarray(ask), jnp.asarray(count), k=16)
    placements = np.asarray(placements)
    used_after = np.asarray(used_after)
    # all counts placed (capacity is ample)
    assert placements.sum() == count.sum()
    # no overcommit on any node/dim
    assert np.all(used_after <= capacity)
    # placements only on feasible nodes
    for u_i in range(4):
        assert np.all(feas[u_i][placements[u_i] > 0])
